"""End-to-end slice: engine + fused pipeline + slow-path control plane.

The SURVEY.md §7 milestone: one DORA cycle where DISCOVER #1 misses to the
slow path and DISCOVER #2 is answered on-device, plus NAT conntrack-hybrid
(first packet punts, second fast-paths), QoS shaping and antispoof drops —
all through the public Engine surface.
"""

import numpy as np
import pytest

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.dhcp_server import DHCPServer
from bng_tpu.control.nat import NATManager
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.ops.antispoof import MODE_STRICT
from bng_tpu.runtime.engine import AntispoofTables, Engine, QoSTables
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.utils.net import ip_to_u32, u32_to_ip

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")
T0 = 1_753_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def stack():
    clock = FakeClock()
    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64, cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"), prefix_len=24,
                        gateway=SERVER_IP, dns_primary=ip_to_u32("1.1.1.1"),
                        lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    qos = QoSTables(nbuckets=256)
    spoof = AntispoofTables(nbuckets=256)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools, fastpath_tables=fastpath,
                        nat_hook=lambda ip, now: nat.allocate_nat(ip, now), clock=clock)
    engine = Engine(fastpath, nat, qos, spoof, batch_size=8,
                    slow_path=server.handle_frame, clock=clock)
    return engine, server, nat, qos, spoof, clock


def client_frame(mac, msg_type, **kw):
    src_ip = kw.pop("src_ip", 0)
    pkt = dhcp_codec.build_request(mac, msg_type, **kw)
    pkt.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, src_ip, 0xFFFFFFFF, 68, 67,
                              pkt.encode().ljust(320, b"\x00"))


def data_frame(src_mac, src_ip, dst_ip, sport, dport, payload=b"data", proto="udp"):
    if proto == "udp":
        return packets.udp_packet(src_mac, SERVER_MAC, src_ip, dst_ip, sport, dport, payload)
    return packets.tcp_packet(src_mac, SERVER_MAC, src_ip, dst_ip, sport, dport, payload)


class TestDORA:
    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_full_dora_then_fastpath(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        mac = bytes.fromhex("02c0ffee0001")

        # DISCOVER #1 -> slow path -> OFFER from server
        r1 = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert r1["tx"] == [] and len(r1["slow"]) == 1
        lane, offer_frame = r1["slow"][0]
        assert offer_frame is not None
        offer = dhcp_codec.decode(packets.decode(offer_frame).payload)
        assert offer.msg_type == dhcp_codec.OFFER
        ip = offer.yiaddr
        assert u32_to_ip(ip).startswith("10.0.0.")

        # REQUEST -> slow path -> ACK + fast-path cache populated
        r2 = engine.process([client_frame(mac, dhcp_codec.REQUEST, requested_ip=ip,
                                          server_id=SERVER_IP)])
        _, ack_frame = r2["slow"][0]
        ack = dhcp_codec.decode(packets.decode(ack_frame).payload)
        assert ack.msg_type == dhcp_codec.ACK
        assert ack.yiaddr == ip
        assert server.stats.ack == 1

        # DISCOVER #2 -> answered ON DEVICE (the fast-path milestone)
        r3 = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert len(r3["tx"]) == 1
        _, dev_frame = r3["tx"][0]
        dev_offer = dhcp_codec.decode(packets.decode(dev_frame).payload)
        assert dev_offer.msg_type == dhcp_codec.OFFER
        assert dev_offer.yiaddr == ip

        # renewal REQUEST also on device
        r4 = engine.process([client_frame(mac, dhcp_codec.REQUEST, requested_ip=ip,
                                          server_id=SERVER_IP)])
        assert len(r4["tx"]) == 1

    def test_release_invalidates_fastpath(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        mac = bytes.fromhex("02c0ffee0002")
        engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        r = engine.process([client_frame(mac, dhcp_codec.REQUEST,
                                         requested_ip=0, server_id=SERVER_IP)])
        ack = dhcp_codec.decode(packets.decode(r["slow"][0][1]).payload)
        ip = ack.yiaddr
        # fast path now answers
        r = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert len(r["tx"]) == 1
        # RELEASE tears down lease + cache
        engine.process([client_frame(mac, dhcp_codec.RELEASE, ciaddr=ip)])
        r = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert r["tx"] == []  # back to slow path
        assert server.stats.release == 1

    def test_lease_expiry_goes_slow_path(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        mac = bytes.fromhex("02c0ffee0003")
        engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        engine.process([client_frame(mac, dhcp_codec.REQUEST, server_id=SERVER_IP)])
        r = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert len(r["tx"]) == 1
        clock.advance(4000)  # beyond 3600s lease
        r = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert r["tx"] == []  # expired -> slow path (renews)


class TestNATFlow:
    def test_conntrack_hybrid(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        sub_mac = bytes.fromhex("02c0ffee0010")
        sub_ip = ip_to_u32("10.0.0.55")
        remote = ip_to_u32("93.184.216.34")
        nat.allocate_nat(sub_ip, T0)

        f = data_frame(sub_mac, sub_ip, remote, 40000, 443)
        # packet 1: new flow -> punt, host creates session
        r1 = engine.process([f])
        assert r1["fwd"] == [] and len(r1["slow"]) == 1
        assert nat.sessions.count == 1

        # packet 2: device SNAT
        r2 = engine.process([f])
        assert len(r2["fwd"]) == 1
        _, out = r2["fwd"][0]
        d = packets.decode(out)
        assert d.src_ip == ip_to_u32("203.0.113.1")
        assert 1024 <= d.src_port <= 65535
        assert d.dst_ip == remote
        nat_port = d.src_port

        # reply from the internet: device DNAT back to subscriber
        reply = packets.udp_packet(SERVER_MAC, sub_mac, remote,
                                   ip_to_u32("203.0.113.1"), 443, nat_port, b"resp")
        r3 = engine.process([reply], from_access=False)
        assert len(r3["fwd"]) == 1
        _, back = r3["fwd"][0]
        db = packets.decode(back)
        assert db.dst_ip == sub_ip
        assert db.dst_port == 40000
        assert db.src_ip == remote

    def test_no_allocation_passes_unnatted(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        f = data_frame(b"\x02" * 6, ip_to_u32("10.0.0.99"), ip_to_u32("8.8.8.8"), 1234, 53)
        r = engine.process([f])
        assert r["fwd"] == [] and len(r["slow"]) == 1
        assert nat.sessions.count == 0  # no port block -> no session

    def test_eim_stable_mapping(self, stack):
        """RFC 4787: same internal ip:port -> same external mapping."""
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.56")
        nat.allocate_nat(sub_ip, T0)
        mac = bytes.fromhex("02c0ffee0011")
        ports = set()
        for dst in ("1.1.1.1", "2.2.2.2", "3.3.3.3"):
            f = data_frame(mac, sub_ip, ip_to_u32(dst), 50000, 443)
            engine.process([f])  # punt -> create
            r = engine.process([f])  # fast path
            d = packets.decode(r["fwd"][0][1])
            ports.add((d.src_ip, d.src_port))
        assert len(ports) == 1  # endpoint-independent


class TestQoS:
    def test_rate_limit_drops(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.60")
        # 8 kbps => 1000 bytes/s; burst 1500
        qos.set_subscriber(sub_ip, down_bps=8000, up_bps=8000, up_burst=1500, down_burst=1500)
        mac = bytes.fromhex("02c0ffee0020")
        big = data_frame(mac, sub_ip, ip_to_u32("8.8.8.8"), 1111, 9999, b"x" * 400)
        frames = [big] * 8
        r = engine.process(frames)
        # 1500-byte bucket / ~442-byte frames -> 3 pass, rest dropped
        assert len(r["dropped"]) >= 4
        assert engine.stats.qos[1] >= 4  # QST_PKTS_DROPPED

    def test_download_direction_rate_limit(self, stack):
        """qos_egress parity (qos_ratelimit.c:126-172): DOWNLOAD shaping
        keys on the post-DNAT destination — network-side lanes must hit
        the qos_down table, not ride for free."""
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.61")
        nat.allocate_nat(sub_ip, T0)
        nat_ip, nat_port = nat.handle_new_flow(
            sub_ip, ip_to_u32("1.2.3.4"), 40000, 443, 17, 600, T0)[:2]
        qos.set_subscriber(sub_ip, down_bps=8000, up_bps=8000,
                           up_burst=1000, down_burst=1000)
        # inbound: internet -> subscriber's public mapping (DNAT resolves)
        down = packets.udp_packet(b"\x04" * 6, SERVER_MAC,
                                  ip_to_u32("1.2.3.4"), nat_ip, 443, nat_port,
                                  b"d" * 458)
        r = engine.process([down] * 3, from_access=False)
        # 2x500B fit the 1000B bucket; the 3rd must drop
        assert len(r["fwd"]) == 2 and len(r["dropped"]) == 1, r

    def test_refill_after_time(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.61")
        qos.set_subscriber(sub_ip, down_bps=80000, up_bps=80000, up_burst=1000, down_burst=1000)
        mac = bytes.fromhex("02c0ffee0021")
        f = data_frame(mac, sub_ip, ip_to_u32("8.8.8.8"), 1111, 9999, b"x" * 800)
        r = engine.process([f])
        assert r["dropped"] == []
        r = engine.process([f])  # bucket nearly empty
        assert len(r["dropped"]) == 1
        clock.advance(1.0)  # 10kB/s refill
        r = engine.process([f])
        assert r["dropped"] == []

    def test_unlimited_rate_passes(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.62")
        qos.set_subscriber(sub_ip, down_bps=0, up_bps=0)
        mac = bytes.fromhex("02c0ffee0022")
        f = data_frame(mac, sub_ip, ip_to_u32("8.8.8.8"), 1111, 9999, b"x" * 1000)
        for _ in range(3):
            r = engine.process([f])
            assert r["dropped"] == []


class TestAntispoof:
    def test_strict_mode_drops_spoofed(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        mac = bytes.fromhex("02c0ffee0030")
        good_ip = ip_to_u32("10.0.0.70")
        spoof.add_binding(mac, good_ip, MODE_STRICT)
        violations = []
        engine.violation_sink = lambda lane, frame: violations.append(lane)

        ok = data_frame(mac, good_ip, ip_to_u32("8.8.8.8"), 1000, 53)
        bad = data_frame(mac, ip_to_u32("10.0.0.71"), ip_to_u32("8.8.8.8"), 1000, 53)
        engine.antispoof.set_config(0, log_violations=True)
        r = engine.process([ok, bad])
        assert r["dropped"] == [1]
        assert violations == [1]

    def test_dhcp_exempt_from_antispoof(self, stack):
        """DISCOVER src 0.0.0.0 must reach the slow path despite strict mode."""
        engine, server, nat, qos, spoof, clock = stack
        mac = bytes.fromhex("02c0ffee0031")
        spoof.add_binding(mac, ip_to_u32("10.0.0.72"), MODE_STRICT)
        r = engine.process([client_frame(mac, dhcp_codec.DISCOVER)])
        assert r["dropped"] == []
        assert r["slow"][0][1] is not None  # got an OFFER


class TestStatsAndExpiry:
    def test_session_counters_and_expiry(self, stack):
        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.80")
        nat.allocate_nat(sub_ip, T0)
        mac = bytes.fromhex("02c0ffee0040")
        f = data_frame(mac, sub_ip, ip_to_u32("9.9.9.9"), 1234, 443)
        engine.process([f])  # create
        for _ in range(3):
            engine.process([f])  # 3 fast-path packets
        vals = engine.fetch_session_vals()
        from bng_tpu.ops.nat44 import SV_PKTS_OUT

        slots = np.nonzero(np.asarray(nat.sessions.used))[0]
        assert len(slots) == 1
        # 1 seeded by the host on create (nat44.c:722 parity) + 3 on device
        assert vals[slots[0], SV_PKTS_OUT] == 4

        # idle expiry (UDP timeout 120s)
        clock.advance(200)
        n = engine.expire()
        assert n == 1
        assert nat.sessions.count == 0 and nat.reverse.count == 0


def test_nat_release_purges_sessions_before_block_reuse():
    """Recycled port blocks must not resurrect the old subscriber's
    reverse-table rows (cross-subscriber traffic leakage)."""
    from bng_tpu.control.nat import NATManager

    nat = NATManager(public_ips=[0xCB007101], ports_per_subscriber=64,
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    a, b = 0x0A000005, 0x0A000006
    nat.allocate_nat(a, now=100)
    got = nat.handle_new_flow(a, 0x5DB8D822, 40000, 443, 17, 100, now=100)
    assert got is not None
    nat_ip, nat_port = got
    # A's reverse row exists
    rkey = [0x5DB8D822, nat_ip, 443, nat_port, 17]
    key = [rkey[0], rkey[1], ((rkey[2] & 0xFFFF) << 16) | (rkey[3] & 0xFFFF), rkey[4]]
    assert nat.reverse.lookup(key) is not None
    nat.release_nat(a, now=200)
    # stale rows are gone
    assert nat.reverse.lookup(key) is None
    assert nat.sessions.used.sum() == 0
    # B gets the recycled block
    blk = nat.allocate_nat(b, now=300)
    assert blk["port_start"] == 1024  # reused A's block


class TestDHCPFastLane:
    """process_dhcp: the DHCP-only device program (latency fast lane).

    Reference hook-order parity: bpf/dhcp_fastpath.c is its own XDP
    program — XDP_TX replies never traverse the TC chain — so a control
    batch runs a several-fold smaller program than the fused step."""

    def test_parity_with_fused_step(self, stack):
        engine, server, *_ , clock = stack
        mac = bytes.fromhex("02deadbe0001")
        disc = client_frame(mac, dhcp_codec.DISCOVER, xid=0x41)
        # DORA through the slow path installs the subscriber
        out = engine.process_dhcp([disc])
        assert len(out["slow"]) == 1 and out["slow"][0][1] is not None
        offered = dhcp_codec.decode(packets.decode(out["slow"][0][1]).payload)
        req = client_frame(mac, dhcp_codec.REQUEST, xid=0x42,
                           requested_ip=offered.yiaddr)
        out = engine.process_dhcp([req])
        assert len(out["slow"]) == 1  # REQUEST completes via slow path too

        # now cached: the SAME DISCOVER must be answered on-device by BOTH
        # programs, byte-for-byte
        fast = engine.process_dhcp([disc])
        assert len(fast["tx"]) == 1, fast
        fused = engine.process([disc])
        assert len(fused["tx"]) == 1, fused
        assert fast["tx"][0][1] == fused["tx"][0][1]

    def test_shared_table_state_both_directions(self, stack):
        engine, server, *_ , clock = stack
        mac = bytes.fromhex("02deadbe0002")
        ip = ip_to_u32("10.0.0.77")
        # install via the host mirror; drain through the DHCP-ONLY step
        engine.fastpath.add_subscriber(mac, pool_id=1, ip=ip,
                                       lease_expiry=T0 + 900)
        disc = client_frame(mac, dhcp_codec.DISCOVER, xid=0x43)
        assert len(engine.process_dhcp([disc])["tx"]) == 1
        # the fused step sees the same (threaded) tables — no re-drain
        assert len(engine.process([disc])["tx"]) == 1

        # and deletion drained through the FUSED step hides it from the
        # dhcp-only program too
        engine.fastpath.remove_subscriber(mac)
        assert len(engine.process([disc])["slow"]) == 1
        assert len(engine.process_dhcp([disc])["tx"]) == 0

    def test_non_dhcp_frames_fall_out_as_slow(self, stack):
        engine, *_ = stack
        junk = data_frame(b"\x02" * 6, ip_to_u32("10.0.0.9"),
                          ip_to_u32("8.8.8.8"), 1234, 80)
        out = engine.process_dhcp([junk])
        assert out["tx"] == [] and len(out["slow"]) == 1


class TestCoADeviceIntegration:
    """RADIUS CoA -> device QoS enforcement, end to end (the reference's
    EBPFQoSUpdaterFunc flow, coa_handler.go:175-460: a policy change must
    reach the packet path with no session restart)."""

    def test_coa_policy_change_enforced_on_next_step(self, stack):
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.control.radius.coa import CoAProcessor, CoAServer
        from bng_tpu.control.radius.policy import PolicyManager, QoSPolicy

        engine, server, nat, qos, spoof, clock = stack
        sub_ip = ip_to_u32("10.0.0.66")
        mac = bytes.fromhex("02c0ffee0066")
        # generous initial policy: everything passes
        qos.set_subscriber(sub_ip, down_bps=1_000_000_000, up_bps=1_000_000_000)
        frames = [data_frame(mac, sub_ip, ip_to_u32("8.8.8.8"), 1111, 9999,
                             b"x" * 400)] * 6
        r = engine.process(frames)
        assert len(r["dropped"]) == 0

        # CoA: throttle to a policy whose burst admits ~2 of these frames
        pm = PolicyManager()
        pm.add(QoSPolicy("throttled", download_bps=8_000, upload_bps=8_000))
        session = type("S", (), {"ip": sub_ip, "mac": mac})()

        def qos_update(ip, policy_name):
            p = pm.get(policy_name)
            # burst pinned to 1000B so the admitted-frame count below is
            # deterministic regardless of the policy's burst_factor
            qos.set_subscriber(ip, down_bps=p.download_bps, up_bps=p.upload_bps,
                               down_burst=1000, up_burst=1000,
                               priority=p.priority)
            return True

        proc = CoAProcessor(find_by_ip=lambda ip: session,
                            qos_update=qos_update, policy_manager=pm)
        srv = CoAServer(b"secret", proc)
        req = rp.RadiusPacket(rp.COA_REQUEST, 9)
        req.add(rp.FRAMED_IP_ADDRESS, sub_ip)
        req.add(rp.FILTER_ID, "throttled")
        resp = rp.RadiusPacket.decode(srv.handle_raw(req.encode(b"secret")))
        assert resp.code == rp.COA_ACK

        # the policy change rides the bounded update drain into the very
        # next device step: 1000B bucket / ~442B frames -> ~2 pass, rest drop
        clock.advance(0.001)
        r2 = engine.process(frames)
        assert len(r2["dropped"]) >= 3, r2


class TestDeviceWalledGarden:
    """Device-side walled-garden gate (beyond the reference, whose garden
    maps reach no bpf program — walledgarden/manager.go:172-178): a
    pre-auth subscriber's packet to an arbitrary IP DROPs on device;
    portal/DNS destinations pass; post-auth everything passes. Membership
    changes flow through the bounded update drain like every table."""

    PORTAL = ip_to_u32("10.255.255.1")
    DNS = ip_to_u32("8.8.8.8")

    def _stack_with_garden(self):
        from bng_tpu.runtime.engine import GardenTables

        clock = FakeClock()
        fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(SERVER_MAC, SERVER_IP)
        pools = PoolManager(fastpath)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=24, gateway=SERVER_IP, lease_time=3600))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        garden = GardenTables(nbuckets=256)
        garden.allow_destination(self.PORTAL, 8080, 6)   # portal TCP
        garden.allow_destination(self.DNS, 53, 0)        # DNS any proto
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            fastpath_tables=fastpath,
                            nat_hook=lambda ip, now: nat.allocate_nat(ip, now),
                            clock=clock)
        engine = Engine(fastpath, nat, garden=garden, batch_size=8,
                        slow_path=server.handle_frame, clock=clock)
        return engine, server, nat, garden, clock

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_pre_auth_drops_on_device_post_auth_passes(self):
        engine, server, nat, garden, clock = self._stack_with_garden()
        mac = bytes.fromhex("02aabb000077")
        sub_ip = ip_to_u32("10.0.0.77")
        nat.allocate_nat(sub_ip, T0)
        nat.handle_new_flow(sub_ip, ip_to_u32("93.184.216.34"), 40000, 443,
                            17, 600, T0)
        garden.set_gardened(sub_ip, True)  # pre-auth

        arbitrary = data_frame(mac, sub_ip, ip_to_u32("93.184.216.34"),
                               40000, 443)
        dns = data_frame(mac, sub_ip, self.DNS, 40000, 53)
        portal = data_frame(mac, sub_ip, self.PORTAL, 40000, 8080,
                            proto="tcp")
        discover = client_frame(mac, dhcp_codec.DISCOVER)
        out = engine.process([arbitrary, dns, portal, discover],
                             from_access=True)
        # arbitrary dest: DROPPED ON DEVICE despite live NAT state
        assert out["dropped"] == [0], out
        # portal + DNS reach the slow path (allowed destinations)
        slow_lanes = [i for i, _ in out["slow"]]
        assert 1 in slow_lanes and 2 in slow_lanes
        # DHCP must still flow (DORA happens while gardened)
        assert 3 in slow_lanes or any(i == 3 for i, _ in out["tx"])

        # post-auth: release via the update drain — next batch forwards
        garden.set_gardened(sub_ip, False)
        out2 = engine.process([arbitrary, dns, portal], from_access=True)
        assert out2["dropped"] == []
        assert 0 in [i for i, _ in out2["fwd"]]  # NAT'd on device again

    def test_gate_never_touches_other_subscribers(self):
        engine, server, nat, garden, clock = self._stack_with_garden()
        gardened_ip = ip_to_u32("10.0.0.88")
        free_ip = ip_to_u32("10.0.0.89")
        garden.set_gardened(gardened_ip, True)
        nat.allocate_nat(free_ip, T0)
        nat.handle_new_flow(free_ip, ip_to_u32("1.2.3.4"), 41000, 443,
                            17, 600, T0)
        blocked = data_frame(bytes.fromhex("02aabb000088"), gardened_ip,
                             ip_to_u32("1.2.3.4"), 41000, 443)
        ok = data_frame(bytes.fromhex("02aabb000089"), free_ip,
                        ip_to_u32("1.2.3.4"), 41000, 443)
        out = engine.process([blocked, ok], from_access=True)
        assert out["dropped"] == [0]
        assert 1 in [i for i, _ in out["fwd"]]

    def test_cli_garden_transitions_drive_device_gate(self):
        """BNGApp: a garden transition + live lease lands in the engine's
        device gate through the composition-root sync."""
        import types

        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.utils.net import mac_to_u64

        app = BNGApp(BNGConfig())
        try:
            dhcp = app.components["dhcp"]
            garden_mgr = app.components["walledgarden"]
            gt = app.components["engine"].garden
            mac = "02:00:00:00:00:61"
            ip = ip_to_u32("10.0.0.61")
            dhcp.leases[mac_to_u64(mac)] = types.SimpleNamespace(
                ip=ip, mac=mac, session_id="s1")
            garden_mgr.add_to_walled_garden(mac)
            assert gt.subscribers.lookup([ip]) is not None
            garden_mgr.release_from_walled_garden(mac)
            assert gt.subscribers.lookup([ip]) is None
            # portal/DNS allowed destinations were seeded from config
            assert (gt.allowed[:, 0] != 0).sum() >= 3
        finally:
            app.close()
