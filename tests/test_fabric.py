"""Cluster control fabric tests (ISSUE 19).

Covers the fabric subsystem end to end: the authenticated UDP transport
(sign/replay/skew/malformed rejection, replay-floor reset), the
deterministic SimTransport (seeded drops, delivery delay, directed
partial partitions), the partition-aware failure detector (suspicion,
accusation quorum, gray serving-word stall, startup grace, reset), the
carve plan's host axis, the RADIUS/CoA fan-out through the slow-path
fleet (MAC-affine auth, relay accounting, degraded cache), the
accounting spool across failover, the resilience probe wall-time fix,
the bng_fabric_* metric families, the ledger n_hosts cohort, and the
two fabric chaos scenarios' byte-determinism.
"""

import json

import pytest

from bng_tpu.cluster.fabric import (FailureDetector, SimTransport,
                                    UDPTransport)
from bng_tpu.control.deviceauth import PSKAuthenticator
from bng_tpu.utils.net import ip_to_u32

pytestmark = pytest.mark.fabric

PSK = "fabric-test-psk-0123456789"


class FakeClock:
    def __init__(self, now=1_700_000_000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def udp_pair(clock=None, psk=PSK, psk_b=None):
    """Two UDP endpoints on loopback, peered both ways."""
    kw = {"clock": clock} if clock is not None else {}
    a = UDPTransport("node-a", PSKAuthenticator(psk=psk), **kw)
    b = UDPTransport("node-b", PSKAuthenticator(psk=psk_b or psk), **kw)
    a.add_peer("node-b", b.addr)
    b.add_peer("node-a", a.addr)
    return a, b


def drain(ep, tries=50):
    """Poll until messages arrive (UDP delivery is async-ish even on
    loopback) or the budget runs out."""
    import time

    for _ in range(tries):
        got = ep.poll()
        if got:
            return got
        time.sleep(0.01)
    return []


class TestUDPTransport:
    def test_signed_beat_roundtrip(self):
        a, b = udp_pair()
        try:
            assert a.send("node-b", "beat", {"served": 3, "work": 7})
            got = drain(b)
            assert len(got) == 1
            msg = got[0]
            assert (msg.src, msg.kind) == ("node-a", "beat")
            assert msg.body == {"served": 3, "work": 7}
            assert msg.seq == 1
            assert b.stats["rx"] == 1
        finally:
            a.close()
            b.close()

    def test_bad_psk_rejected(self):
        a, b = udp_pair(psk_b="a-different-psk-9876543210")
        try:
            a.send("node-b", "beat", {})
            assert drain(b, tries=20) == []
            assert b.stats["rx_bad_sig"] == 1
            assert b.stats["rx"] == 0
        finally:
            a.close()
            b.close()

    def _wire(self, transport, src, seq, ts, kind="beat", body=None):
        """A raw datagram signed with the receiver's own PSK (what a
        legitimate sender with that seq/ts would put on the wire)."""
        from bng_tpu.cluster.fabric.transport import (FABRIC_VERSION,
                                                      _canonical)

        body = body or {}
        sig = transport.authenticator.sign_message(
            _canonical(src, seq, ts, kind, body))
        return json.dumps({"v": FABRIC_VERSION, "src": src, "seq": seq,
                           "ts": ts, "kind": kind, "body": body,
                           "sig": sig}).encode()

    def test_replay_skew_malformed_counted(self):
        clock = FakeClock()
        rx = UDPTransport("rx", PSKAuthenticator(psk=PSK), clock=clock)
        try:
            now = clock()
            fresh = self._wire(rx, "peer", 5, now)
            assert rx._verify(fresh) is not None
            # same seq again = replay; an OLDER seq is also a replay
            assert rx._verify(fresh) is None
            assert rx._verify(self._wire(rx, "peer", 4, now)) is None
            assert rx.stats["rx_replay"] == 2
            # timestamp outside the skew window
            assert rx._verify(
                self._wire(rx, "peer", 6, now - 10_000.0)) is None
            assert rx.stats["rx_skew"] == 1
            # garbage and schema-violating datagrams
            assert rx._verify(b"not json at all") is None
            assert rx._verify(b'{"v":1,"src":"x"}') is None
            assert rx.stats["rx_malformed"] == 2
            assert rx.stats["rx"] == 1
        finally:
            rx.close()

    def test_reset_peer_clears_replay_floor(self):
        """Standby promotion: the slot's new process restarts seq at 1.
        Without the reset every fresh beat would read as a replay."""
        clock = FakeClock()
        rx = UDPTransport("rx", PSKAuthenticator(psk=PSK), clock=clock)
        try:
            assert rx._verify(self._wire(rx, "peer", 9, clock())) is not None
            assert rx._verify(self._wire(rx, "peer", 1, clock())) is None
            rx.reset_peer("peer")
            assert rx._verify(self._wire(rx, "peer", 1, clock())) is not None
        finally:
            rx.close()


class TestSimTransport:
    def test_seeded_drops_deterministic(self):
        def run(seed):
            clock = FakeClock()
            hub = SimTransport(clock, seed=seed)
            a, b = hub.endpoint("a"), hub.endpoint("b")
            a.add_peer("b")
            hub.set_drop("a", "b", 0.5)
            pattern = []
            for i in range(50):
                a.send("b", "beat", {"i": i})
                pattern.extend(m.body["i"] for m in b.poll())
            return pattern, hub.stats["dropped"]

        p1, d1 = run(11)
        p2, d2 = run(11)
        p3, _ = run(12)
        assert p1 == p2 and d1 == d2
        assert 0 < d1 < 50
        assert p1 != p3  # a different seed drops differently

    def test_delay_holds_until_clock_passes(self):
        clock = FakeClock()
        hub = SimTransport(clock, seed=0)
        a, b = hub.endpoint("a"), hub.endpoint("b")
        a.add_peer("b")
        hub.set_delay("a", "b", 2.0)
        a.send("b", "beat", {})
        assert b.poll() == []
        clock.advance(1.0)
        assert b.poll() == []
        clock.advance(1.5)
        assert len(b.poll()) == 1

    def test_partial_partition_is_per_link(self):
        """partition(a, b) severs exactly a<->b; both still reach c —
        the NEAT shape, not a binary netsplit."""
        clock = FakeClock()
        hub = SimTransport(clock, seed=0)
        eps = {n: hub.endpoint(n) for n in ("a", "b", "c")}
        for n, ep in eps.items():
            for p in eps:
                if p != n:
                    ep.add_peer(p)
        hub.partition("a", "b")
        for src in ("a", "b", "c"):
            for dst in eps[src].peers:
                eps[src].send(dst, "beat", {})
        got = {n: sorted(m.src for m in eps[n].poll()) for n in eps}
        assert got == {"a": ["c"], "b": ["c"], "c": ["a", "b"]}
        assert hub.stats["cut"] == 2
        hub.heal("a", "b")
        eps["a"].send("b", "beat", {})
        assert [m.src for m in eps["b"].poll()] == ["a"]

    def test_oneway_partition(self):
        clock = FakeClock()
        hub = SimTransport(clock, seed=0)
        a, b = hub.endpoint("a"), hub.endpoint("b")
        a.add_peer("b")
        b.add_peer("a")
        hub.partition_oneway("a", "b")
        a.send("b", "beat", {})
        b.send("a", "beat", {})
        assert b.poll() == []
        assert len(a.poll()) == 1


def mesh(clock, seed=0, n=3, **det_kw):
    """An n-node detector mesh over one sim hub, everyone watching
    everyone (quorum defaults: majority of observers)."""
    hub = SimTransport(clock, seed=seed)
    ids = [f"n{i}" for i in range(n)]
    dets = {}
    for nid in ids:
        ep = hub.endpoint(nid)
        for p in ids:
            if p != nid:
                ep.add_peer(p)
        kw = dict(clock=clock, beat_interval_s=0.5,
                  suspicion_threshold=3, startup_grace_s=0.0)
        kw.update(det_kw)
        dets[nid] = FailureDetector(nid, ep, **kw)
    for nid in ids:
        for p in ids:
            if p != nid:
                dets[nid].watch(p, now=clock())
    return hub, ids, dets


def beat_rounds(clock, dets, rounds, silent=(), bodies=None):
    for _ in range(rounds):
        for nid, d in dets.items():
            if nid in silent:
                continue
            body = (bodies or {}).get(nid, {})
            d.beat(served=body.get("served", 0), work=body.get("work", 0))
        for d in dets.values():
            d.tick(clock())
        clock.advance(0.5)


class TestFailureDetector:
    def test_suspect_then_recover_counts_partition(self):
        clock = FakeClock()
        _, _, dets = mesh(clock, n=2)
        beat_rounds(clock, dets, 3)
        assert dets["n0"].views["n1"].state == "up"
        beat_rounds(clock, dets, 5, silent=("n1",))
        # 2-node mesh: observers of n1 = just n0, quorum 1 -> down...
        # unless n0 withholds? observers//2+1 = 1, so silence IS fatal
        assert dets["n0"].views["n1"].state == "down"
        assert dets["n0"].verdicts["down"] == 1

    def test_no_quorum_no_down_in_partial_partition(self):
        clock = FakeClock()
        hub, _, dets = mesh(clock, n=3)
        beat_rounds(clock, dets, 3)
        hub.partition("n0", "n1")
        beat_rounds(clock, dets, 8)
        # each split side suspects the other, the common neighbour
        # vouches (by not accusing): 1 accuser < quorum 2
        assert dets["n0"].views["n1"].state == "suspect"
        assert dets["n1"].views["n0"].state == "suspect"
        assert dets["n2"].views["n0"].state == "up"
        assert dets["n2"].views["n1"].state == "up"
        assert sum(d.verdicts["down"] for d in dets.values()) == 0
        # accusations piggybacked on beats reached the neighbour
        assert dets["n2"].views["n1"].accused_by == {"n0"}
        hub.heal_all()
        beat_rounds(clock, dets, 6)
        assert dets["n0"].views["n1"].state == "up"
        assert dets["n0"].views["n1"].partitions_observed == 1

    def test_gray_needs_no_quorum(self):
        """work advances, served stalls, beats keep flowing: GRAY off
        the member's own signed beats, no accusation round needed."""
        clock = FakeClock()
        _, _, dets = mesh(clock, n=3, gray_beats=4)
        ctr = {"n": 0}

        def round_(wedge):
            ctr["n"] += 8
            bodies = {nid: {"served": ctr["n"], "work": ctr["n"]}
                      for nid in dets}
            if wedge:
                bodies["n1"]["served"] = 32  # frozen after round 4
            beat_rounds(clock, dets, 1, bodies=bodies)

        for _ in range(4):
            round_(wedge=False)
        assert dets["n0"].views["n1"].state == "up"
        for _ in range(6):
            round_(wedge=True)
        assert dets["n0"].views["n1"].state == "gray"
        assert dets["n0"].probe("n1") is False
        assert dets["n0"].probe("n2") is True
        # the healthy members never flap
        assert dets["n0"].views["n2"].state == "up"

    def test_startup_grace_shields_never_beaten_peer(self):
        clock = FakeClock()
        ep = SimTransport(clock, seed=0).endpoint("solo")
        det = FailureDetector("solo", ep, clock=clock,
                              beat_interval_s=0.5, suspicion_threshold=3,
                              startup_grace_s=10.0, quorum=1)
        det.watch("spawning", now=clock())
        clock.advance(5.0)  # 10 missed beats, but inside the grace
        assert det.tick(clock()) == []
        assert det.views["spawning"].state == "up"
        clock.advance(6.0)  # grace expired, still never beaten
        assert det.tick(clock()) == [("spawning", "down")]

    def test_reset_wipes_history_and_rearms_grace(self):
        clock = FakeClock()
        ep = SimTransport(clock, seed=0).endpoint("solo")
        det = FailureDetector("solo", ep, clock=clock,
                              beat_interval_s=0.5, suspicion_threshold=3,
                              startup_grace_s=10.0, quorum=1)
        det.watch("m", now=clock())
        clock.advance(20.0)
        det.tick(clock())
        assert det.views["m"].state == "down"
        assert det.probe("m") is False
        det.reset("m", now=clock())
        assert det.views["m"].state == "up"
        assert det.probe("m") is True
        clock.advance(5.0)  # fresh grace window for the promoted slot
        assert det.tick(clock()) == []

    def test_status_deterministic_shape(self):
        clock = FakeClock()
        _, _, dets = mesh(clock, n=2)
        beat_rounds(clock, dets, 2)
        st = dets["n0"].status()
        assert st["node_id"] == "n0"
        assert st["beats_tx"] == 2 and st["beats_rx"] == 2
        assert set(st["peers"]) == {"n1"}
        assert json.dumps(st, sort_keys=True)  # JSON-serializable


class TestPlanHostAxis:
    def test_hosts_interleave_the_deal(self):
        from bng_tpu.cluster.plan import initial_plan

        plan = initial_plan(ip_to_u32("10.0.0.0"), 16, ["a", "b", "c"],
                            hosts={"a": "h1", "b": "h1", "c": "h2"})
        dealt = {i: [blk.index for blk in p.blocks]
                 for i, p in plan.members.items()}
        # round-robin across sorted host groups: h1(a,b) x h2(c)
        assert dealt == {"a": [0, 3], "b": [2], "c": [1]}
        assert plan.n_hosts == 2
        assert plan.hosts() == {"a": "h1", "b": "h1", "c": "h2"}

    def test_no_hosts_is_exactly_the_legacy_deal(self):
        from bng_tpu.cluster.plan import initial_plan

        legacy = initial_plan(ip_to_u32("10.0.0.0"), 16, ["a", "b", "c"])
        blank = initial_plan(ip_to_u32("10.0.0.0"), 16, ["a", "b", "c"],
                             hosts={"a": "", "b": "", "c": ""})
        assert {i: [blk.index for blk in p.blocks]
                for i, p in legacy.members.items()} \
            == {"a": [0, 3], "b": [1], "c": [2]} \
            == {i: [blk.index for blk in p.blocks]
                for i, p in blank.members.items()}
        assert legacy.n_hosts == 1

    def test_serialization_and_legacy_restore(self):
        from bng_tpu.cluster.plan import ClusterPlan, initial_plan

        plan = initial_plan(ip_to_u32("10.0.0.0"), 16, ["a", "b"],
                            hosts={"a": "h1", "b": "h2"})
        back = ClusterPlan.from_dict(plan.to_dict())
        assert back.hosts() == {"a": "h1", "b": "h2"}
        # a pre-host-axis checkpoint restores to the unplaced legacy
        d = plan.to_dict()
        for p in d["members"].values():
            p.pop("host")
        legacy = ClusterPlan.from_dict(d)
        assert legacy.hosts() == {"a": "", "b": ""}
        assert legacy.n_hosts == 1

    def test_replan_carries_hosts_and_survivors_pinned(self):
        from bng_tpu.cluster.plan import initial_plan, replan

        plan = initial_plan(ip_to_u32("10.0.0.0"), 16, ["a", "b"],
                            hosts={"a": "h1", "b": "h2"})
        before = {i: [blk.index for blk in p.blocks]
                  for i, p in plan.members.items()}
        # unchanged membership -> the SAME plan object (no new epoch)
        assert replan(plan, ["a", "b"]) is plan
        # a joiner on a new host deals from the free list only
        grown = replan(plan, ["a", "b", "c"], hosts={"c": "h3"})
        after = {i: [blk.index for blk in p.blocks]
                 for i, p in grown.members.items()}
        assert after["a"] == before["a"] and after["b"] == before["b"]
        assert grown.hosts() == {"a": "h1", "b": "h2", "c": "h3"}
        assert grown.n_hosts == 3


# ---------------------------------------------------------------------------
# RADIUS/CoA fan-out through the slow-path fleet
# ---------------------------------------------------------------------------

from bng_tpu.control.fleet import shard_for_mac  # noqa: E402
from bng_tpu.control.radius import packet as rp  # noqa: E402
from bng_tpu.control.radius.client import (RadiusClient,  # noqa: E402
                                           RadiusServerConfig)
from tests.test_fleet import (SERVER_IP, discover, dora,  # noqa: E402
                              make_pools, mac_of, reply_packet, request)
from tests.test_radius import SECRET, FakeRadiusServer  # noqa: E402


def make_radius_fleet(n=2, users=None):
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet

    pools = make_pools()
    spec = FleetSpec.from_pool_manager(
        bytes.fromhex("02aabbccdd01"), SERVER_IP, pools)
    spec.radius_servers = [RadiusServerConfig(
        "10.0.0.5", secret=SECRET, timeout_s=0.05, retries=1)]
    spec.radius_nas_id = "bng-test"
    from bng_tpu.control.fleet import SlowPathFleet as _F

    fleet = _F(spec, n, pools, mode="inline")
    users = users if users is not None else {
        "": {"password": "", "attrs": [(rp.FILTER_ID, "gold"),
                                       (rp.SESSION_TIMEOUT, 600)]}}
    servers = []
    for w in fleet._inline:
        assert w.radius is not None
        srv = FakeRadiusServer(users=users)
        w.radius.transport = srv
        servers.append(srv)
    return fleet, servers


class TestRadiusFanout:
    def test_auth_lands_on_mac_affine_worker(self):
        fleet, servers = make_radius_fleet(n=2)
        try:
            macs = [mac_of(i) for i in range(16)]
            leased = dora(fleet, macs)
            assert len(leased) == 16
            # every worker authenticated exactly its steered MACs —
            # auth affinity IS dhcp affinity (same FNV-1a32 hash)
            want = {0: 0, 1: 0}
            for m in macs:
                want[shard_for_mac(m, 2)] += 1
            assert {w: fleet._inline[w].auth_requests
                    for w in (0, 1)} == want
            assert all(want[w] > 0 for w in (0, 1))
            # the worker's own client socket served them (no parent)
            for w, srv in enumerate(servers):
                auths = [r for _, _, r in srv.requests
                         if r.code == rp.ACCESS_REQUEST]
                assert len(auths) == want[w]
            # Session-Timeout capped the lease via the profile
            lease = next(iter(fleet._inline[0].server.leases.values()))
            assert lease.qos_policy == "gold"
        finally:
            fleet.close()

    def test_reject_naks_and_degraded_cache_serves_outage(self):
        fleet, _ = make_radius_fleet(n=2)
        try:
            m = mac_of(3)
            w = shard_for_mac(m, 2)
            leased = dora(fleet, [m])
            assert len(leased) == 1
            # outage: every auth times out from here on
            fleet._inline[w].radius.transport = lambda *a: None
            # the known subscriber's lease expires; re-auth times out;
            # the worker-local degraded cache answers instead
            fleet._inline[w].server.leases.clear()
            fleet._inline[w].server._offers.clear()
            out = dora(fleet, [m], xid_base=500)
            assert len(out) == 1
            assert fleet._inline[w].auth_degraded == 1
            # a NEVER-seen subscriber has no cached profile: NAK
            m2 = next(mm for mm in (mac_of(100 + i) for i in range(32))
                      if shard_for_mac(mm, 2) == w)
            got = fleet.handle_batch([(0, discover(m2, 900))])
            offer = got[0][1]
            if offer is not None:  # OFFER precedes auth (auth on REQUEST)
                o = reply_packet(offer)
                got = fleet.handle_batch(
                    [(0, request(m2, o.yiaddr, SERVER_IP, 901))])
                from bng_tpu.control import dhcp_codec
                assert reply_packet(got[0][1]).msg_type == dhcp_codec.NAK
        finally:
            fleet.close()

    def test_coa_qos_on_owner_and_disconnect(self):
        from bng_tpu.control import dhcp_codec

        fleet, _ = make_radius_fleet(n=2)
        try:
            m = mac_of(5)
            leased = dora(fleet, [m])
            ip = leased[m]
            w = shard_for_mac(m, 2)
            r = fleet.handle_coa("qos", mac=m, policy_name="premium")
            assert r == {"found": True, "ip": ip, "worker": w,
                         "relayed": False}
            assert fleet.coa_handled == 1 and fleet.coa_relayed == 0
            import bng_tpu.utils.net as _net
            lease = next(iter(fleet._inline[w].server.leases.values()))
            assert lease.qos_policy == "premium"
            # disconnect force-expires; the next REQUEST is a fresh DORA
            r = fleet.handle_coa("disconnect", ip=ip)
            assert r["found"] and r["worker"] == w
            assert fleet._inline[w].server.leases == {}
            # unknown target: counted miss
            r = fleet.handle_coa("locate", ip=ip_to_u32("10.9.9.9"))
            assert not r["found"] and fleet.coa_misses == 1
        finally:
            fleet.close()

    def test_coa_relay_counted_when_lease_off_steer(self):
        fleet, _ = make_radius_fleet(n=2)
        try:
            m = mac_of(7)
            leased = dora(fleet, [m])
            w = shard_for_mac(m, 2)
            other = 1 - w
            # the lease moved off its steered shard (a resize shape):
            # the steered probe misses, the scan finds it, relay counted
            from bng_tpu.utils.net import mac_to_u64
            lease = fleet._inline[w].server.leases.pop(mac_to_u64(m))
            fleet._inline[other].server.leases[mac_to_u64(m)] = lease
            r = fleet.handle_coa("locate", mac=m)
            assert r == {"found": True, "ip": leased[m], "worker": other,
                         "relayed": True}
            assert fleet.coa_relayed == 1
        finally:
            fleet.close()

    def test_worker_stats_carry_radius_lane(self):
        fleet, _ = make_radius_fleet(n=2)
        try:
            dora(fleet, [mac_of(i) for i in range(8)])
            fleet.handle_coa("locate", mac=mac_of(0))
            snap = fleet.stats_snapshot()
            assert snap["coa_handled"] == 1
            per = [w for w in snap["per_worker"] if w]
            assert sum(w["auth_requests"] for w in per) == 8
            assert all("radius" in w and w["radius"]["auth_ok"] >= 0
                       for w in per)
        finally:
            fleet.close()


class TestAccountingSpoolFailover:
    def test_promoted_standby_replays_spool_once(self, tmp_path):
        """The active's RADIUS dies mid-session; its stop spools. The
        active then dies; the promoted standby recovers the spool and
        replays it — each record lands exactly once, octets never
        double-count."""
        from bng_tpu.control.radius.accounting import AccountingManager

        spool = str(tmp_path / "acct.spool")
        clock = FakeClock()
        live = FakeRadiusServer()
        client = RadiusClient(
            [RadiusServerConfig("10.0.0.5", secret=SECRET,
                                timeout_s=0.05, retries=1)],
            transport=live, clock=clock)
        active = AccountingManager(client, interim_interval_s=60,
                                   spool_path=spool, clock=clock)
        assert active.start("s1", "alice", ip_to_u32("10.0.0.9"))
        active.update_counters("s1", 1111, 2222)
        clock.advance(61.0)
        assert active.interim_tick(clock()) == 1
        active.update_counters("s1", 5555, 7777)
        # the RADIUS server goes dark: the stop spools instead of sending
        client.transport = lambda *a: None
        assert active.stop("s1") is False
        assert len(active.pending) == 1
        # ACTIVE DIES here (no more ticks). The standby promotes with
        # the same spool path and a healthy server:
        client2 = RadiusClient(
            [RadiusServerConfig("10.0.0.5", secret=SECRET,
                                timeout_s=0.05, retries=1)],
            transport=live, clock=clock)
        standby = AccountingManager(client2, interim_interval_s=60,
                                    spool_path=spool, clock=clock)
        assert standby.retry_tick() == 1
        assert standby.retry_tick() == 0  # nothing left to replay
        stops = [r for _, _, r in live.requests
                 if r.code == rp.ACCOUNTING_REQUEST
                 and r.get_int(rp.ACCT_STATUS_TYPE) == rp.ACCT_STOP]
        assert len(stops) == 1
        assert stops[0].get_int(rp.ACCT_INPUT_OCTETS) == 5555
        assert stops[0].get_int(rp.ACCT_OUTPUT_OCTETS) == 7777

    def test_orphaned_session_closed_with_lost_carrier(self, tmp_path):
        from bng_tpu.control.radius.accounting import AccountingManager

        spool = str(tmp_path / "acct.spool")
        clock = FakeClock()
        live = FakeRadiusServer()

        def client():
            return RadiusClient(
                [RadiusServerConfig("10.0.0.5", secret=SECRET,
                                    timeout_s=0.05, retries=1)],
                transport=live, clock=clock)

        active = AccountingManager(client(), spool_path=spool, clock=clock)
        active.start("s2", "bob", ip_to_u32("10.0.0.10"))
        # crash with the session open: the standby must close it out
        standby = AccountingManager(client(), spool_path=spool, clock=clock)
        assert standby.retry_tick() == 1
        stops = [r for _, _, r in live.requests
                 if r.code == rp.ACCOUNTING_REQUEST
                 and r.get_int(rp.ACCT_STATUS_TYPE) == rp.ACCT_STOP]
        assert len(stops) == 1
        assert stops[0].get_int(rp.ACCT_TERMINATE_CAUSE) \
            == rp.TERM_LOST_CARRIER


class TestResilienceProbeWallTime:
    def test_stalling_probe_credits_elapsed_ticks(self):
        """A radius probe that blocks for multiple check intervals
        (socket timeout against a black-holed server) must credit the
        burned wall-time, or detection takes threshold * stall."""
        from bng_tpu.control.resilience import ResilienceManager

        wall = FakeClock(0.0)

        def stalling_resolver():
            wall.advance(12.0)  # each probe eats 12s of wall-time
            return False

        mgr = ResilienceManager(
            nexus_healthy=lambda: True,
            radius_healthy=stalling_resolver,
            check_interval_s=5.0, failure_threshold=3,
            probe_clock=wall)
        mgr.tick(10.0)
        # one stalled probe = 1 + 12//5 = 3 ticks >= threshold: down NOW
        assert mgr.radius_down is True
        assert mgr.degraded_auth_active

    def test_fast_probe_still_needs_threshold_ticks(self):
        from bng_tpu.control.resilience import ResilienceManager

        wall = FakeClock(0.0)
        mgr = ResilienceManager(
            nexus_healthy=lambda: True,
            radius_healthy=lambda: False,
            check_interval_s=5.0, failure_threshold=3,
            probe_clock=wall)
        mgr.tick(10.0)
        mgr.tick(20.0)
        assert mgr.radius_down is False
        mgr.tick(30.0)
        assert mgr.radius_down is True

    def test_recovery_resets_the_count(self):
        from bng_tpu.control.resilience import ResilienceManager

        wall = FakeClock(0.0)
        healthy = {"v": False}
        mgr = ResilienceManager(
            nexus_healthy=lambda: True,
            radius_healthy=lambda: healthy["v"],
            check_interval_s=5.0, failure_threshold=3,
            probe_clock=wall)
        mgr.tick(10.0)
        mgr.tick(20.0)
        healthy["v"] = True
        mgr.tick(30.0)
        assert mgr._radius_fails == 0 and not mgr.radius_down


class TestFabricMetrics:
    def _status(self, state="up", accusers=()):
        return {"node_id": "coordinator", "beats_tx": 10, "beats_rx": 20,
                "verdicts": {"suspect": 1, "gray": 0, "down": 2},
                "partitions_observed": 3,
                "peers": {"bng-a": {"state": state, "beats_rx": 20,
                                    "stalled_beats": 0,
                                    "accused_by": list(accusers),
                                    "served": 5, "work": 5}},
                "transport": {"tx": 10, "rx": 20, "rx_bad_sig": 1,
                              "rx_replay": 2, "rx_skew": 0,
                              "rx_malformed": 4}}

    def test_collect_fabric_families(self):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        m.collect_fabric(self._status(state="gray",
                                      accusers=("coordinator",)))
        assert m.fabric_beats_tx.value() == 10
        assert m.fabric_beats_rx.value() == 20
        assert m.fabric_verdicts.value(verdict="down") == 2
        assert m.fabric_partitions.value() == 3
        assert m.fabric_member_state.value(member="bng-a", state="gray") == 1
        assert m.fabric_member_state.value(member="bng-a", state="up") == 0
        assert m.fabric_member_suspicion.value(member="bng-a") == 1
        assert m.fabric_rx_rejected.value(reason="bad_sig") == 1
        assert m.fabric_rx_rejected.value(reason="malformed") == 4

    def test_departed_member_labels_drop(self):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        m.collect_fabric(self._status())
        gone = self._status()
        gone["peers"] = {}
        m.collect_fabric(gone)
        assert m.fabric_member_suspicion.labeled() == []
        assert m.fabric_member_state.labeled() == []

    def test_record_cluster_routes_fabric_block(self):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        m.record_cluster({"members": {}, "recarves": 0, "failovers": 0,
                          "shed_frames": 0, "refused_removes": 0,
                          "fabric": self._status()})
        assert m.fabric_beats_rx.value() == 20

    def test_fleet_scrape_carries_fanout_counters(self):
        from bng_tpu.control.metrics import BNGMetrics

        fleet, _ = make_radius_fleet(n=2)
        try:
            dora(fleet, [mac_of(i) for i in range(8)])
            fleet.handle_coa("locate", mac=mac_of(1))
            m = BNGMetrics()
            m.collect_fleet(fleet)
            per = {w: fleet._inline[w].auth_requests for w in (0, 1)}
            for w, n in per.items():
                assert m.fabric_auth_shard.value(worker=str(w)) == n
            assert m.fabric_coa_relayed.value() == 0
        finally:
            fleet.close()


class TestLedgerHosts:
    def _line(self, i, n_hosts=None, value=10.0):
        line = {"metric": "serve Mpps", "value": value, "unit": "Mpps",
                "run_id": f"r{i}", "ts": f"2026-08-0{(i % 7) + 1}",
                "schema_version": 1, "batch": 1024,
                "env": {"backend": "tpu", "device_kind": "TPU v4"}}
        if n_hosts is not None:
            line["n_hosts"] = n_hosts
        return line

    def test_legacy_lines_default_to_one_host(self):
        from bng_tpu.telemetry.ledger import cohort_key, n_hosts

        legacy = self._line(0)
        assert n_hosts(legacy) == 1
        stamped = self._line(1, n_hosts=1)
        assert cohort_key(legacy) == cohort_key(stamped)
        assert n_hosts({"env": {"n_hosts": 3}}) == 3
        assert n_hosts({"n_hosts": "junk"}) == 1

    def test_multi_host_lines_refuse_single_host_history(self, tmp_path):
        from bng_tpu.telemetry import ledger as lg

        path = tmp_path / "bench_runs.jsonl"
        for i in range(5):
            lg.append(str(path), self._line(i))
        cand = self._line(9, n_hosts=3, value=35.0)
        lg.append(str(path), cand)
        rep = lg.gate_file(str(path))
        assert rep.rc == 3  # incomparable cohort, never a regression
        note = " ".join(rep.notes)
        # the refusal names BOTH widths
        assert "hosts=3" in note and "hosts=1" in note


class TestFabricChaosScenarios:
    def test_partial_partition_ok_and_deterministic(self):
        from bng_tpu.chaos.scenarios import cluster_partial_partition

        a = cluster_partial_partition(7)
        assert a["ok"], a
        assert a["down_verdicts"] == 0 and a["failovers"] == 0
        b = cluster_partial_partition(7)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)

    def test_gray_member_ok_and_deterministic(self):
        from bng_tpu.chaos.scenarios import cluster_gray_member

        a = cluster_gray_member(5)
        assert a["ok"], a
        assert a["failovers"] == 1 and a["gray_verdicts"] >= 1
        b = cluster_gray_member(5)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)

    def test_scenarios_registered(self):
        from bng_tpu.chaos.scenarios import SCENARIOS

        assert "cluster_partial_partition" in SCENARIOS
        assert "cluster_gray_member" in SCENARIOS


@pytest.mark.slow
class TestProcessFabric:
    def test_udp_beats_and_sigkill_failover(self):
        """The ISSUE 19 acceptance shape: a process-mode cluster whose
        members beat over the UDP fabric; SIGKILL one member and the
        fabric detector (not a pipe flag) drives exactly one failover,
        after which the promoted slot's beats resume."""
        import os
        import signal
        import time

        from bng_tpu.cluster.coordinator import ClusterCoordinator

        coord = ClusterCoordinator(
            mode="process", fabric=True, n_workers=1,
            fabric_beat_interval_s=0.1, fabric_suspicion_threshold=3,
            ha_probe_interval_s=0.1, ha_failover_delay_s=0.2,
            ha_failure_threshold=2)
        try:
            coord.add_instances(["bng-a", "bng-b"])
            deadline = time.time() + 60
            while time.time() < deadline:
                coord.tick()
                st = coord.fabric_detector.status()
                if st["peers"] and all(p["beats_rx"] >= 3
                                       for p in st["peers"].values()):
                    break
                time.sleep(0.05)
            peers = coord.fabric_detector.status()["peers"]
            assert all(v["beats_rx"] >= 3 for v in peers.values()), peers

            os.kill(coord.members["bng-a"].instance.pid, signal.SIGKILL)
            deadline = time.time() + 60
            while time.time() < deadline and coord.failovers == 0:
                coord.tick()
                time.sleep(0.05)
            assert coord.failovers == 1
            assert ("bng-a", "down") in coord.fabric_events
            assert coord.members["bng-a"].role == "promoted"

            # the promoted slot's fresh process beats again (the replay
            # floor was reset, or its seq=1 beats would all drop)
            deadline = time.time() + 60
            ok = False
            while time.time() < deadline:
                coord.tick()
                v = coord.fabric_detector.views["bng-a"]
                if v.beats_rx >= 2 and v.state == "up":
                    ok = True
                    break
                time.sleep(0.05)
            assert ok, coord.fabric_detector.status()
        finally:
            coord.close()
