"""Structural HLO regression tests — the op-shape contracts perf relies on.

The round-2/3 QoS bottleneck was invisible to every behavioral test: the
kernel was correct but its probe lowered to sixteen 1-word-wide gathers
(~7ns/element serialized on v5e) instead of two wide row gathers. These
tests pin the STRUCTURE of the lowered programs (StableHLO, backend
independent) so a refactor that quietly reintroduces a narrow-gather
probe or a gather explosion fails CI — PERF_NOTES.md §2 has the numbers.
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp


def _stablehlo(fn, *args) -> str:
    return jax.jit(fn).lower(*args).as_text()


def _count(pattern: str, text: str) -> int:
    return len(re.findall(pattern, text))


class TestQoSLookupShape:
    def _lowered(self):
        from bng_tpu.ops.qos import qos_kernel
        from bng_tpu.runtime.engine import QoSTables

        qos = QoSTables(nbuckets=1 << 10)
        for i in range(64):
            qos.set_subscriber((10 << 24) | (i + 2), down_bps=1_000_000,
                               up_bps=1_000_000)
        table = qos.up.device_state()
        B = 1024
        ips = jnp.asarray(((10 << 24) + 2 + np.arange(B) % 64).astype(np.uint32))
        lens = jnp.full((B,), 900, dtype=jnp.uint32)
        active = jnp.ones((B,), dtype=bool)
        return _stablehlo(
            lambda t, i, l: qos_kernel(i, l, active, t, qos.geom,
                                       jnp.uint32(1)),
            table, ips, lens)

    def test_probe_is_wide_row_gathers(self):
        """The packed probe: both rows[b] gathers carry full 32-word rows
        (slice_sizes = [1,32]) — the narrow [S,1]/[S] probe must not come
        back."""
        hlo = self._lowered()
        # every gather whose operand is the [NB,32] rows array must take
        # whole rows: "slice_sizes = array<i64: 1, 32>" in stablehlo syntax
        row_gathers = _count(r"slice_sizes = array<i64: 1, 32>", hlo)
        assert row_gathers == 2, f"expected 2 packed-row gathers, got {row_gathers}"

    def test_total_gather_budget(self):
        """Whole-kernel gather budget (currently 3, ALL wide rows: 2
        packed-row probes + 1 sorted-operand [B,8] pack row — token state
        lives inside the probe rows, the way-select is a one-hot sum).
        The r2 kernel had 16 narrow probe gathers alone; hold the line."""
        hlo = self._lowered()
        total = _count(r'"stablehlo\.gather"', hlo)  # ops, not attrs
        assert total <= 3, f"gather explosion: {total} gathers in qos_kernel"

    def test_no_narrow_gathers(self):
        """Every gather in the kernel must carry >=8-word rows — 1-word
        slices are the measured ~7ns/element serialized shape."""
        hlo = self._lowered()
        narrow = _count(r"slice_sizes = array<i64: 1>", hlo)
        narrow += _count(r"slice_sizes = array<i64: 1, 1>", hlo)
        assert narrow == 0, f"{narrow} narrow gathers in qos_kernel"

    def test_scatter_budget(self):
        """Currently 6: 1 packed-row unsort, 1 wide way-row token
        writeback, 4 scalar stats adds."""
        hlo = self._lowered()
        scatters = _count(r'"stablehlo\.scatter"', hlo)
        assert scatters <= 6, f"unexpected scatter count: {scatters}"


class TestDHCPFastpathShape:
    def test_table_probes_are_wide_row_gathers(self):
        """All three fast-path table probes (sub K=2, vlan K=1, cid K=8)
        must gather packed bucket rows: 4x [1,32] (sub+vlan, KW=8) and
        2x [1,64] (cid, KW=16). The 18 narrow key/used gathers of the
        unpacked layout must not come back."""
        from bng_tpu.ops.dhcp import dhcp_fastpath
        from bng_tpu.ops.parse import parse_batch
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        fp = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=16)
        fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
        tables = fp.device_tables()
        B, L = 256, 512
        pkt = jnp.zeros((B, L), dtype=jnp.uint8)
        ln = jnp.full((B,), 300, dtype=jnp.uint32)

        def step(tables, pkt, ln):
            par = parse_batch(pkt, ln)
            res = dhcp_fastpath(pkt, ln, par, tables, fp.geom, jnp.uint32(1))
            return res.is_reply, res.out_pkt, res.out_len

        hlo = _stablehlo(step, tables, pkt, ln)
        assert _count(r"slice_sizes = array<i64: 1, 32>", hlo) == 4
        assert _count(r"slice_sizes = array<i64: 1, 64>", hlo) == 2
        # per-lane packet-byte reads ([1,1]) are fine; whole-column
        # table-probe gathers ([S,1] operands) are the serialized shape
        narrow_1d = _count(r"slice_sizes = array<i64: 1>(?!,)", hlo)
        assert narrow_1d == 0, f"{narrow_1d} 1-D narrow gathers"


class TestNAT44Shape:
    def test_probes_are_wide_row_gathers(self):
        """NAT's three cuckoo tables (sessions K=4, reverse K=4, sub_nat
        K=1 — all KW=8) must probe as packed [1,32] bucket rows; the
        kernel + accounting pass stay within a tight gather/scatter
        budget (narrow whole-table gathers are the serialized shape)."""
        from bng_tpu.control.nat import NATManager
        from bng_tpu.ops.nat44 import nat44_kernel, nat44_update_sessions
        from bng_tpu.ops.parse import parse_batch
        from bng_tpu.utils.net import ip_to_u32

        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        tables = nat.device_tables()
        B, L = 256, 512
        pkt = jnp.zeros((B, L), dtype=jnp.uint8)
        ln = jnp.full((B,), 200, dtype=jnp.uint32)

        def step(tables, pkt, ln):
            par = parse_batch(pkt, ln)
            res = nat44_kernel(pkt, ln, par, tables, nat.geom, jnp.uint32(1))
            sess = nat44_update_sessions(tables.sessions, res, par, ln,
                                         keep=res.translated,
                                         now_s=jnp.uint32(1))
            return res.out_pkt, res.translated, sess

        hlo = _stablehlo(step, tables, pkt, ln)
        row_probes = _count(r"slice_sizes = array<i64: 1, 32>", hlo)
        assert row_probes >= 6, f"packed probes missing: {row_probes}"
        narrow_1d = _count(r"slice_sizes = array<i64: 1>(?!,)", hlo)
        assert narrow_1d == 0, f"{narrow_1d} 1-D narrow gathers"
        total = _count(r'"stablehlo\.gather"', hlo)
        assert total <= 22, f"gather explosion: {total}"
        scatters = _count(r'"stablehlo\.scatter"', hlo)
        assert scatters <= 4, f"scatter explosion: {scatters}"


class TestShardedExchangeShape:
    def test_two_collectives_per_lookup(self):
        """The sharded lookup must stay exactly two all-to-alls (request +
        packed response) — a third collective means someone unpacked the
        response path (3x ICI latency)."""
        from jax.sharding import PartitionSpec as P

        from bng_tpu.ops.table import HostTable, TableGeom, lookup
        from bng_tpu.parallel.sharded import AXIS, _shard_map, make_mesh

        N = 4
        mesh = make_mesh(N)
        t = HostTable(nbuckets=64, key_words=2, val_words=4)
        g = TableGeom(nbuckets=64, stash=64, axis=AXIS, n_shards=N)
        st = jax.tree.map(lambda *xs: jnp.stack(xs),
                          *[t.device_state() for _ in range(N)])
        q = jnp.zeros((N * 32, 2), dtype=jnp.uint32)

        def local(tabs1, q):
            tabs = jax.tree.map(lambda x: x[0], tabs1)
            r = lookup(tabs, q, g)
            return r.found, r.vals

        f = _shard_map(local, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
                       out_specs=(P(AXIS), P(AXIS)))
        hlo = _stablehlo(f, st, q)
        n_a2a = _count(r"all_to_all", hlo)
        assert n_a2a == 2, f"expected 2 all_to_alls, got {n_a2a}"


class TestFastLaneCompileShapeBudget:
    """VERDICT r3 weak #6: process_dhcp compiles one program per pow2
    batch bucket. Pin the bucket set so a latency sweep over arbitrary
    control-batch sizes can never quietly spend a chip window compiling."""

    def test_bucket_set_is_bounded_and_exact(self):
        from bng_tpu.runtime.engine import Engine

        buckets = {Engine.dhcp_batch_bucket(n) for n in range(0, 20_000, 7)}
        buckets |= {Engine.dhcp_batch_bucket(n) for n in
                    (1, 63, 64, 65, 127, 128, 8191, 8192, 8193, 100_000)}
        assert buckets == {64, 128, 256, 512, 1024, 2048, 4096, 8192}
        # monotone + covering: every n <= cap fits its bucket
        for n in range(1, 8193, 11):
            assert n <= Engine.dhcp_batch_bucket(n)

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_engine_reuses_bucket_shapes(self):
        """Distinct frame counts in one bucket must share one compiled
        program (counted via the jit cache of the DHCP-only step)."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        fastpath = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(bytes.fromhex("02aabbccdd01"),
                                   ip_to_u32("10.0.0.1"))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        engine = Engine(fastpath, nat, batch_size=8,
                        clock=lambda: 1_753_000_000.0)

        def disc(i):
            mac = bytes([2, 0xAB, 0, 0, 0, i])
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68,
                                      67, p.encode().ljust(320, b"\x00"))

        sizes = [1, 3, 17, 50, 64]  # all in the 64-bucket
        for s in sizes:
            engine.process_dhcp([disc(i) for i in range(s)])
        cache = engine._dhcp_step._cache_size()
        assert cache == 1, f"expected 1 compiled fast-lane shape, got {cache}"
        engine.process_dhcp([disc(i) for i in range(65)])  # 128-bucket
        assert engine._dhcp_step._cache_size() == 2

    def test_over_cap_batch_splits_not_crashes(self, monkeypatch):
        """len(frames) > DHCP_BATCH_CAP splits into capped chunks with
        lane indices re-based (review r4: the cap must not regress large
        process_dhcp calls into a ValueError)."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        fastpath = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(bytes.fromhex("02aabbccdd01"),
                                   ip_to_u32("10.0.0.1"))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        engine = Engine(fastpath, nat, batch_size=8,
                        clock=lambda: 1_753_000_000.0)
        monkeypatch.setattr(Engine, "DHCP_BATCH_CAP", 64)

        def disc(i):
            mac = bytes([2, 0xAC, 0, 0, i // 256, i % 256])
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68,
                                      67, p.encode().ljust(320, b"\x00"))

        frames = [disc(i) for i in range(150)]  # 3 chunks of <=64
        out = engine.process_dhcp(frames)
        lanes = sorted(i for i, _ in out["tx"] + out["slow"])
        assert lanes == list(range(150))  # every lane accounted, re-based


class TestGardenGateShape:
    """The garden gate must stay in the wide-gather regime (PERF_NOTES §2:
    narrow 1-word gathers serialize to ~7ns/element on v5e)."""

    def test_gather_budget_isolated_kernel(self):
        """The gate in isolation (src/dst ip + port/proto as inputs):
        a bounded handful of WIDE gathers — two bucket-row probes + the
        value-row gather + stash — never a per-word gather explosion."""
        import jax
        from bng_tpu.ops.garden import garden_kernel
        from bng_tpu.ops.parse import Parsed
        from bng_tpu.runtime.engine import GardenTables

        g = GardenTables(nbuckets=1 << 10)
        B = 1024

        def step(state, allowed, src_ip, dst_ip, dst_port, proto, ok):
            parsed = Parsed(**{f: (src_ip if f == "src_ip" else
                                   dst_ip if f == "dst_ip" else
                                   dst_port if f == "dst_port" else
                                   proto if f == "proto" else
                                   ok if f == "is_ipv4" else
                                   jnp.zeros((B,), dtype=jnp.uint32))
                               for f in Parsed._fields})
            res = garden_kernel(parsed, ok, state, g.geom, allowed)
            return res.gate_drop, res.stats

        u32 = jnp.zeros((B,), dtype=jnp.uint32)
        txt = jax.jit(step).lower(
            g.subscribers.device_state(), jnp.asarray(g.allowed),
            u32, u32, u32, u32, jnp.ones((B,), dtype=bool)).as_text()
        # exactly the device_lookup structure: 2 wide bucket-row probes +
        # 1 wide value-row gather (stash is a broadcast compare). The
        # [64,1] column reads of the tiny static allowed array are fine;
        # a [capacity,1] column gather over the subscriber table is the
        # serialized shape and must never appear.
        assert _count(r"slice_sizes = array<i64: 1, 32>", txt) == 2
        assert _count(r"slice_sizes = array<i64: 1, 8>", txt) == 1
        assert _count(r"slice_sizes = array<i64: 1>(?!,)", txt) == 0
        cap = (1 << 10) * 4
        assert _count(rf"slice_sizes = array<i64: {cap}, 1>", txt) == 0
