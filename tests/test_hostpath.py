"""Vectorized host serving path (ISSUE 14): byte-identity matrix.

The contract under test: BNG_HOST_PATH=vector does the SAME work as the
scalar per-frame path with batch-native NumPy — same classifications,
same steering, same admission verdicts AND counters, same ring outputs
byte for byte, same express replies — over a corpus that includes every
edge the scalar oracles guard (runts, truncated VLAN tags, QinQ, the
PPPoE LCP/IPCP precedence edge from the PR 12 fix, relayed giaddr
frames, fragments, non-DHCP port-67 transit, random junk). The scalar
functions are the oracle; any divergence is a correctness bug.

Markers: `hostpath` (make verify-hostpath, <60s); the compile-heavy
end-to-end scheduler A/B is additionally @slow (the tier-1 budget
satellite).
"""

from __future__ import annotations

import numpy as np
import pytest

from bng_tpu.control import packets
from bng_tpu.control.admission import (AdmissionConfig, AdmissionController,
                                       peek_dhcp)
from bng_tpu.control.dhcp_codec import (ACK, DISCOVER, INFORM, RELEASE,
                                        REQUEST, ExpressWireTemplate,
                                        build_request)
from bng_tpu.runtime import hostpath
from bng_tpu.runtime.ring import (FLAG_FROM_ACCESS, PyRing, VERDICT_DROP,
                                  VERDICT_TX, classify_dhcp, shard_of)

pytestmark = pytest.mark.hostpath


# ---------------------------------------------------------------------------
# the frame corpus
# ---------------------------------------------------------------------------

def _vlan_wrap(frame: bytes, tags) -> bytes:
    out = frame[:12]
    for tpid, vid in tags:
        out += tpid.to_bytes(2, "big") + vid.to_bytes(2, "big")
    return out + frame[12:]

def _discover(rng, mac, relayed=False, tags=(), bcast=True, t=DISCOVER):
    p = build_request(mac, t, xid=int(rng.integers(1 << 31)),
                      giaddr=(0x0A000001 if relayed else 0), broadcast=bcast)
    # standard 300-byte BOOTP padding (the bench's _discover_row shape;
    # the express fixed-offset option scan requires the padded tail)
    f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                           p.encode().ljust(300, b"\x00"))
    return _vlan_wrap(f, tags) if tags else f

def _pppoe(proto: int, inner: bytes = b"") -> bytes:
    return (b"\x02" * 6 + b"\x04" * 6 + b"\x88\x64" + b"\x11\x00"
            + (1).to_bytes(2, "big")
            + (len(inner) + 2).to_bytes(2, "big")
            + proto.to_bytes(2, "big") + inner)

def _fragment(src, dst) -> bytes:
    f = bytearray(packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src, dst,
                                     68, 67, b"x" * 60))
    f[20] = 0x20  # MF flag: fragmented, no parseable L4
    return bytes(f)


def build_corpus(seed: int = 7) -> list:
    rng = np.random.default_rng(seed)
    inner_ip = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, 0x0A0A0A0A,
                                  0x08080808, 1234, 80, b"y" * 40)[14:]
    corpus = []
    for i in range(30):
        mac = b"\x02" + bytes(int(x) for x in rng.integers(0, 255, 5))
        t = [DISCOVER, REQUEST, RELEASE, INFORM][i % 4]
        corpus.append(_discover(rng, mac, t=t))
        corpus.append(_discover(rng, mac, relayed=True, t=t))
        corpus.append(_discover(rng, mac, tags=[(0x8100, 10)], bcast=False))
        corpus.append(_discover(rng, mac, tags=[(0x88A8, 5), (0x8100, 7)]))
        corpus.append(packets.udp_packet(
            b"\x02" * 6, b"\x04" * 6, int(rng.integers(1 << 32)),
            int(rng.integers(1 << 32)), int(rng.integers(1024, 65535)),
            443, b"x" * int(rng.integers(20, 300))))
    # PPPoE session data vs control — the PR 12 precedence edge: the
    # PPP-proto compare must be the full 16-bit 0x0021, never
    # `hi<<8 | (lo==0x21)`; LCP (0xC021) and IPCP (0x8021) frames whose
    # LOW byte is 0x21 must fall to the sticky MAC hash
    corpus.append(_pppoe(0x0021, inner_ip))
    corpus.append(_pppoe(0xC021, b"\x01\x01\x00\x04"))
    corpus.append(_pppoe(0x8021, b"\x01\x01\x00\x04"))
    corpus.append(_pppoe(0x0021))  # session data, truncated inner
    # port-67 transit that is NOT DHCP (no BOOTP magic)
    corpus.append(packets.udp_packet(b"\x02" * 6, b"\x04" * 6, 5, 6, 68,
                                     67, b"notdhcp" * 40))
    corpus.append(_fragment(7, 8))
    # runts / truncations of every shape above
    for f in list(corpus[:12]):
        for cut in (0, 5, 13, 14, 16, 17, 18, 20, 22, 33, 41, 60, 240,
                    len(f) - 1):
            corpus.append(f[:cut])
    for _ in range(30):
        corpus.append(bytes(rng.integers(
            0, 255, int(rng.integers(1, 300)), dtype=np.uint8).tolist()))
    return corpus


CORPUS = build_corpus()
PUB_IPS = {0x04040404: 1, 0x08080808: 2, 0x01010101: 99}


# ---------------------------------------------------------------------------
# kernel identity vs the scalar oracles
# ---------------------------------------------------------------------------

class TestKernelIdentity:
    def test_classify(self):
        buf, lens = hostpath.pack_rows(CORPUS)
        got = hostpath.classify_dhcp_batch(buf, lens.astype(np.int64))
        for i, f in enumerate(CORPUS):
            assert int(got[i]) == classify_dhcp(f), (i, f.hex())

    @pytest.mark.parametrize("n_shards", [1, 2, 4, 8, 64])
    @pytest.mark.parametrize("from_access", [True, False])
    def test_shard_of(self, n_shards, from_access):
        buf, lens = hostpath.pack_rows(CORPUS)
        lens = lens.astype(np.int64)
        fl = np.full(len(CORPUS),
                     FLAG_FROM_ACCESS if from_access else 0, np.uint32)
        if from_access:
            fl |= hostpath.classify_dhcp_batch(buf, lens)
        keys = np.sort(np.fromiter(PUB_IPS.keys(), dtype=np.uint64))
        vals = np.array([PUB_IPS[int(k)] for k in keys], dtype=np.int64)
        got = hostpath.shard_of_batch(buf, lens, fl, n_shards, keys, vals)
        for i, f in enumerate(CORPUS):
            assert int(got[i]) == shard_of(f, int(fl[i]), n_shards,
                                           PUB_IPS), (n_shards, i, f.hex())

    def test_peek_dhcp(self):
        buf, lens = hostpath.pack_rows(CORPUS)
        msg, mac, parsed = hostpath.peek_dhcp_batch(buf,
                                                    lens.astype(np.int64))
        for i, f in enumerate(CORPUS):
            sp = peek_dhcp(f)
            if sp is None:
                assert not parsed[i], (i, f.hex())
            else:
                assert parsed[i], (i, f.hex())
                assert (int(msg[i]), int(mac[i])) == sp, (i, f.hex())

    def test_fnv(self):
        from bng_tpu.utils.net import fnv1a32

        rows = np.frombuffer(
            b"".join(f[:6].ljust(6, b"\0") for f in CORPUS if f),
            dtype=np.uint8).reshape(-1, 6)
        got = hostpath.fnv1a32_cols(rows)
        for i, row in enumerate(rows):
            assert int(got[i]) == fnv1a32(row.tobytes())

    def test_pack_roundtrip(self):
        frames = [f for f in CORPUS if f]
        buf, lens = hostpath.pack_rows(frames)
        for i, f in enumerate(frames):
            assert buf[i, : len(f)].tobytes() == f
            assert not buf[i, len(f):].any()
            assert lens[i] == len(f)

    def test_pack_rejects_oversize(self):
        out = np.zeros((2, 16), np.uint8)
        with pytest.raises(ValueError, match="exceeds staging slot"):
            hostpath.pack_into([b"x" * 17, b"y"], out,
                               np.zeros(2, np.uint32))

    def test_staging_pool_clears_stale_rows(self):
        pool = hostpath.StagingPool(16, depth=2)
        for _ in range(2):  # cycle the whole pool with 3-row batches
            pool.stage([b"aaaa", b"bbbb", b"cccc"], 8)
        pkt, length = pool.stage([b"zz"], 8)
        assert length[0] == 2 and not pkt[1:].any() and not length[1:].any()

    def test_staging_pool_ensure_depth_grows_live_rings(self):
        # review finding: configurable scheduler depths must widen the
        # cycle — a buffer may not be handed out again until at least
        # `depth` later stage() calls have cycled past it
        pool = hostpath.StagingPool(8, depth=2)
        a, _ = pool.stage([b"a"], 4)
        pool.ensure_depth(5)
        assert pool.depth == 5
        seen = [a] + [pool.stage([b"x"], 4)[0] for _ in range(4)]
        assert all(x is not a for x in seen[1:])  # 4 distinct successors
        b, _ = pool.stage([b"y"], 4)
        assert b is a  # cycles back only after depth=5 hand-outs
        pool.ensure_depth(3)  # never shrinks
        assert pool.depth == 5


# ---------------------------------------------------------------------------
# PyRing: end-to-end byte identity
# ---------------------------------------------------------------------------

def _drive_ring(host_path: str, n_shards: int, sharded: bool,
                B: int = 64, slot: int = 512, depth: int = 64,
                nframes: int = 256, frame_size: int = 600) -> list:
    r = PyRing(nframes=nframes, frame_size=frame_size, depth=depth,
               n_shards=n_shards, host_path=host_path)
    for ip, s in PUB_IPS.items():
        if s < n_shards:
            r.steer_pub_ip(ip, s)
    src = [f for f in CORPUS if len(f) <= min(slot, frame_size)]
    log = [("pushed", r.rx_push_batch(src[:100], from_access=True)
            + r.rx_push_batch(src[100:140], from_access=False))]
    rng = np.random.default_rng(3)
    for _ in range(50):
        if not r.rx_pending():
            break
        out = np.zeros((B, slot), np.uint8)
        ol = np.zeros(B, np.uint32)
        fl = np.zeros(B, np.uint32)
        n = (r.assemble_sharded(out, ol, fl) if sharded
             else r.assemble(out, ol, fl))
        if n == 0:
            break
        nn = B if sharded else n
        log.append(("asm", n, out.tobytes(), ol.tobytes(), fl.tobytes()))
        v = rng.integers(0, 4, nn).astype(np.uint8)
        reply = np.zeros((nn, slot), np.uint8)
        rl = rng.integers(20, slot, nn).astype(np.uint32)
        for k in range(nn):
            reply[k, : rl[k]] = rng.integers(0, 255, int(rl[k]))
        r.complete(v, reply, rl, nn)
    while True:
        got = r.tx_pop() or r.fwd_pop() or r.slow_pop()
        if got is None:
            break
        log.append(("pop", got[0], got[1]))
    log.append(("stats", tuple(sorted(r.stats().items()))))
    log.append(("free", r.free_frames()))
    return log


class TestRingIdentity:
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_assemble_complete_pop(self, n_shards):
        assert (_drive_ring("scalar", n_shards, False)
                == _drive_ring("vector", n_shards, False))

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_sharded_assemble(self, n_shards):
        assert (_drive_ring("scalar", n_shards, True, B=n_shards * 32)
                == _drive_ring("vector", n_shards, True, B=n_shards * 32))

    def test_pressure_paths(self):
        # tiny ring: free-pool pressure at push, queue overflow at
        # complete — the scalar-fallback decisions must match exactly
        def drive(hp):
            r = PyRing(nframes=20, frame_size=600, depth=6, n_shards=2,
                       host_path=hp)
            src = [f for f in CORPUS if 0 < len(f) <= 500]
            log = [("pushed", r.rx_push_batch(src[:40])),
                   ("stats", tuple(sorted(r.stats().items())))]
            out = np.zeros((16, 512), np.uint8)
            ol = np.zeros(16, np.uint32)
            fl = np.zeros(16, np.uint32)
            n = r.assemble(out, ol, fl)
            reply = np.zeros((n, 512), np.uint8)
            r.complete(np.full(n, VERDICT_TX, np.uint8), reply,
                       np.full(n, 100, np.uint32), n)
            log.append(("stats2", tuple(sorted(r.stats().items())),
                        r.free_frames()))
            while True:
                p = r.tx_pop()
                if p is None:
                    break
                log.append(p)
            return log
        assert drive("scalar") == drive("vector")

    def test_tx_pop_batch_identity(self):
        def drive(hp):
            r = PyRing(nframes=64, frame_size=600, depth=32, host_path=hp)
            r.rx_push_batch([f for f in CORPUS if 20 < len(f) < 500][:20])
            o = np.zeros((32, 512), np.uint8)
            ln = np.zeros(32, np.uint32)
            g = np.zeros(32, np.uint32)
            n = r.assemble(o, ln, g)
            rep = np.zeros((n, 512), np.uint8)
            rep[:, :77] = 9
            r.complete(np.full(n, VERDICT_TX, np.uint8), rep,
                       np.full(n, 77, np.uint32), n)
            return r.tx_pop_batch(5) + r.tx_pop_batch()
        assert drive("scalar") == drive("vector")

    def test_oversized_reply_spill(self):
        # device reply wider than the UMEM slot: the vector path spills
        # to bytes; payloads must still match the scalar path
        def drive(hp):
            r = PyRing(nframes=16, frame_size=128, depth=8, host_path=hp)
            r.rx_push_batch([b"\x01" * 60, b"\x02" * 60])
            o = np.zeros((8, 256), np.uint8)
            ln = np.zeros(8, np.uint32)
            g = np.zeros(8, np.uint32)
            n = r.assemble(o, ln, g)
            rep = np.arange(8 * 256, dtype=np.uint32).astype(np.uint8)
            rep = rep.reshape(8, 256)
            r.complete(np.full(n, VERDICT_TX, np.uint8), rep,
                       np.full(n, 200, np.uint32), n)  # 200 > 128 slot
            return r.tx_pop_batch() + [r.tx_pop()]
        assert drive("scalar") == drive("vector")

    @pytest.mark.parametrize("batch", [
        [b"", b""],                     # ALL-empty: flat would be size 0
        [b"", b"", b"\x01\x02\x03"],    # empty mixed with a runt
    ])
    def test_zero_length_frames_accepted_like_scalar(self, batch):
        # review finding: empty and all-empty batches must not index a
        # zero-width matrix or an empty flat buffer — the scalar oracle
        # ACCEPTS zero-length frames (shard 0, slow path)
        outs = {}
        for hp in ("scalar", "vector"):
            r = PyRing(nframes=16, frame_size=128, depth=8, n_shards=2,
                       host_path=hp)
            got = r.rx_push_batch(list(batch))
            outs[hp] = (got, r.rx_pending(),
                        tuple(sorted(r.stats().items())))
        assert outs["scalar"] == outs["vector"]
        assert outs["scalar"][0] == len(batch)

    def test_vector_zero_tail_reuse(self):
        # a slot that held a LONG frame then a short one must not leak
        # the long occupant's tail into a later assemble
        r = PyRing(nframes=4, frame_size=256, depth=4, host_path="vector")
        out = np.zeros((4, 256), np.uint8)
        ol = np.zeros(4, np.uint32)
        fl = np.zeros(4, np.uint32)
        r.rx_push_batch([b"\xaa" * 200])
        n = r.assemble(out, ol, fl)
        r.complete(np.full(n, VERDICT_DROP, np.uint8),
                   np.zeros((n, 256), np.uint8), np.zeros(n, np.uint32), n)
        r.rx_push_batch([b"\xbb" * 10])
        out[:] = 0xEE  # dirty caller staging too
        n = r.assemble(out, ol, fl)
        assert n == 1 and ol[0] == 10
        assert out[0, :10].tobytes() == b"\xbb" * 10
        assert not out[0, 10:].any()


# ---------------------------------------------------------------------------
# admission: batched admit identity
# ---------------------------------------------------------------------------

def _admission_frames():
    rng = np.random.default_rng(5)
    macs = [b"\x02" + bytes(int(x) for x in rng.integers(0, 255, 5))
            for _ in range(64)]
    frames = [_discover(rng, m, t=[DISCOVER, REQUEST, RELEASE, INFORM][i % 4])
              for i, m in enumerate(macs)]
    frames.append(b"\x00" * 40)  # unparsable
    frames.append(packets.udp_packet(b"\x02" * 6, b"\x04" * 6, 1, 2, 99,
                                     443, b"zz"))  # non-DHCP
    return macs, frames


def _run_admission(vec: bool, scenario: str):
    macs, frames = _admission_frames()
    cfg = AdmissionConfig(inbox_capacity=32, request_hard_capacity=48,
                          deadline_ms=50, offer_ttl_s=10)
    ac = AdmissionController(cfg, clock=lambda: 1000.0)
    for m in macs[:10]:
        ac.note_offer(int.from_bytes(m, "big"), now=999.0)
    for m in macs[10:20]:
        ac.note_ack(int.from_bytes(m, "big"))
    for m in macs[5:8]:  # expired offers (ttl 10s)
        ac.note_offer(int.from_bytes(m, "big"), now=980.0)
    now = 1000.0
    n = len(frames)
    workers = np.array([i % 3 for i in range(n)], dtype=np.int64)
    if scenario == "unpressured":
        enq = np.full(n, now - 0.001)
    elif scenario == "no_enq":
        enq = None
    elif scenario == "deadline":
        enq = np.array([now - (0.2 if i % 3 == 0 else 0.001)
                        for i in range(n)])
    else:  # inbox pressure: the scalar-fallback path
        cfg.inbox_capacity = 4
        enq = np.full(n, now - 0.001)
    if vec:
        buf, lens = hostpath.pack_rows(frames)
        out = ac.admit_batch(frames, workers, buf, lens.astype(np.int64),
                             now, enq).tolist()
    else:
        depth: dict = {}
        out = []
        for i, f in enumerate(frames):
            w = int(workers[i])
            ok, _ = ac.admit(f, depth.get(w, 0), now,
                             None if enq is None else float(enq[i]))
            out.append(ok)
            if ok:
                depth[w] = depth.get(w, 0) + 1
    return out, ac.stats_snapshot(), sorted(ac._offered.items())


class TestAdmissionIdentity:
    @pytest.mark.parametrize("scenario", ["unpressured", "no_enq",
                                          "deadline", "pressure"])
    def test_verdicts_counters_state(self, scenario):
        assert _run_admission(False, scenario) == _run_admission(True,
                                                                 scenario)

    def test_admit_batch_without_buf_packs_lazily(self):
        # buf=None: the breached subset is packed on demand
        macs, frames = _admission_frames()
        cfg = AdmissionConfig(deadline_ms=50, offer_ttl_s=10)
        ac = AdmissionController(cfg, clock=lambda: 1000.0)
        n = len(frames)
        enq = np.array([1000.0 - (0.2 if i % 2 == 0 else 0.001)
                        for i in range(n)])
        got = ac.admit_batch(frames, np.zeros(n, np.int64), None,
                             hostpath.frame_lens(frames), 1000.0, enq)
        ref = _run_admission(False, "deadline")  # not same inputs; just
        del ref  # ensure the lazy path ran without error
        assert got.dtype == bool and len(got) == n

    def test_leased_mac_stale_offer_never_evicted(self):
        # review finding: scalar is_known short-circuits on _leased and
        # never evicts the mac's stale _offered entry; the batch path
        # must leave identical state (offer_cap FIFO order depends on it)
        mac = 0x02AABBCCDD01
        outs = {}
        for vec in (False, True):
            ac = AdmissionController(
                AdmissionConfig(offer_ttl_s=10), clock=lambda: 1000.0)
            ac.note_ack(mac)
            ac.note_offer(mac, now=900.0)  # stale re-offer while leased
            if vec:
                known = ac.is_known_batch(
                    np.array([mac], dtype=np.uint64), 1000.0)
                assert bool(known[0])
            else:
                assert ac.is_known(mac, 1000.0)
            outs[vec] = sorted(ac._offered.items())
        assert outs[False] == outs[True] == [(mac, 900.0)]

    def test_chaos_armed_falls_back_to_scalar(self):
        # an armed fault plan must route admit_batch through the
        # per-frame oracle so fault_point hit accounting is preserved
        from bng_tpu.chaos import faults
        from bng_tpu.chaos.faults import FaultInjector, FaultPlan, FaultSpec

        macs, frames = _admission_frames()
        ac = AdmissionController(AdmissionConfig(), clock=lambda: 1000.0)
        n = len(frames)
        plan = FaultPlan(specs=[FaultSpec(
            point="admission.admit", kind="force_shed", at_hit=2)])
        inj = FaultInjector(plan)
        faults.arm(inj)
        try:
            got = ac.admit_batch(frames, np.zeros(n, np.int64), None,
                                 hostpath.frame_lens(frames), 1000.0,
                                 None)
        finally:
            faults.disarm()
        # exactly hit #2 shed by chaos — per-frame hit order preserved
        assert not got[1] and got.sum() == n - 1
        assert ac.stats.shed.get("chaos", 0) == 1


# ---------------------------------------------------------------------------
# fleet: vector pre-pass identity
# ---------------------------------------------------------------------------

def _build_fleet(host_path: str, fallback: bool):
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
    from bng_tpu.control.pool import Pool, PoolManager

    prev = hostpath.HOST_PATH
    hostpath.HOST_PATH = host_path
    try:
        pm = PoolManager()
        pm.add_pool(Pool(pool_id=1, network=(10 << 24), prefix_len=16,
                         gateway=(10 << 24) | 1, lease_time=600))
        fb = (lambda frame: b"FB" + frame[:4]) if fallback else None
        fl = SlowPathFleet(
            FleetSpec.from_pool_manager(b"\x00\x11\x22\x33\x44\x55",
                                        (10 << 24) | 1, pm),
            3, pm, mode="inline", fallback=fb, clock=lambda: 1000.0)
    finally:
        hostpath.HOST_PATH = prev
    assert fl.host_path == host_path
    return fl


class TestFleetIdentity:
    @pytest.mark.parametrize("fallback", [False, True])
    def test_handle_batch(self, fallback):
        rng = np.random.default_rng(9)
        macs = [b"\x02" + bytes(int(x) for x in rng.integers(0, 255, 5))
                for _ in range(120)]
        items, lane = [], 0
        for m in macs:
            items.append((lane, _discover(rng, m)))
            lane += 1
            if lane % 7 == 0:
                items.append((lane, packets.udp_packet(
                    m, b"\x04" * 6, 5, 6, 99, 443, b"v6ish")))
                lane += 1
        reqs = [(i, _discover(rng, m, t=REQUEST))
                for i, m in enumerate(macs[:40])]
        outs = {}
        for hp in ("scalar", "vector"):
            fl = _build_fleet(hp, fallback)
            r1 = fl.handle_batch(list(items))
            r2 = fl.handle_batch(list(reqs))  # REQUEST-after-OFFER path
            outs[hp] = (r1, r2, fl.admission.stats_snapshot(),
                        fl.fallback_frames)
        assert outs["scalar"] == outs["vector"]

    def test_runt_steering(self):
        # frames shorter than 12 bytes steer to worker 0 on both paths
        items = [(0, b"\x01\x02"), (1, _discover(np.random.default_rng(1),
                                                 b"\x02abcde"))]
        outs = {}
        for hp in ("scalar", "vector"):
            fl = _build_fleet(hp, False)
            outs[hp] = (fl.handle_batch(list(items)),
                        fl.admission.stats_snapshot())
        assert outs["scalar"] == outs["vector"]


# ---------------------------------------------------------------------------
# express wire template: batched render identity
# ---------------------------------------------------------------------------

class TestRenderBatchIdentity:
    @pytest.mark.parametrize("relayed,use_bcast,tags", [
        (False, True, ()),
        (False, False, ()),
        (True, False, ()),
        (False, True, [(0x8100, 12)]),
        (False, False, [(0x88A8, 3), (0x8100, 9)]),
    ])
    def test_groups(self, relayed, use_bcast, tags):
        from bng_tpu.ops.express import parse_express

        rng = np.random.default_rng(11)
        tmpl = ExpressWireTemplate(
            server_mac=b"\x02\xaa\xbb\xcc\xdd\x01",
            server_ip=0x0A000001, gateway=0x0A000001, dns1=0x01010101,
            dns2=0x08080808, lease_t=3600, mask=0xFFFF0000,
            reply_type=ACK)
        frames = []
        for k in range(17):
            mac = b"\x02" + bytes(int(x) for x in rng.integers(0, 255, 5))
            f = _discover(rng, mac, relayed=relayed, tags=list(tags),
                          bcast=use_bcast)
            frames.append(f)
        descs = [parse_express(f) for f in frames]
        assert all(d is not None for d in descs)
        d0 = descs[0]
        yiaddrs = rng.integers(1, 1 << 32, len(frames)).astype(np.uint32)
        want = [tmpl.render(f, d.vlan_off, d.dhcp_off, relayed,
                            use_bcast, int(y))
                for f, d, y in zip(frames, descs, yiaddrs)]
        fmat, _ = hostpath.pack_rows(frames)
        got = tmpl.render_batch(fmat, d0.vlan_off, d0.dhcp_off, relayed,
                                use_bcast, yiaddrs)
        assert got == want


# ---------------------------------------------------------------------------
# engine staging identity
# ---------------------------------------------------------------------------

class TestEngineStaging:
    def _engines(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables

        out = {}
        for hp in ("scalar", "vector"):
            prev = hostpath.HOST_PATH
            hostpath.HOST_PATH = hp
            try:
                fp = FastPathTables(sub_nbuckets=1 << 8,
                                    vlan_nbuckets=1 << 6,
                                    cid_nbuckets=1 << 6)
                out[hp] = Engine(fp, NATManager(public_ips=[0xCB007101]),
                                 batch_size=32, pkt_slot=256)
            finally:
                hostpath.HOST_PATH = prev
        return out

    def test_pack_frames_identity(self):
        engines = self._engines()
        frames = [f for f in CORPUS if 0 < len(f) <= 256][:30]
        a = engines["scalar"]._pack_frames(frames, 32)
        b = engines["vector"]._pack_frames(frames, 32)
        assert (a[0] == b[0]).all() and (a[1] == b[1]).all()
        # pooled buffer reuse keeps the padding region clean
        b2 = engines["vector"]._pack_frames(frames[:3], 32)
        a2 = engines["scalar"]._pack_frames(frames[:3], 32)
        assert (a2[0] == b2[0]).all() and (a2[1] == b2[1]).all()

    def test_pack_frames_oversize_raises(self):
        engines = self._engines()
        for eng in engines.values():
            with pytest.raises(ValueError, match="pkt_slot"):
                eng._pack_frames([b"x" * 300], 32)


# ---------------------------------------------------------------------------
# chaos parity: armed plans force the scalar oracles everywhere
# ---------------------------------------------------------------------------

class TestChaosParity:
    def test_fleet_scalar_under_armed_plan(self):
        from bng_tpu.chaos import faults
        from bng_tpu.chaos.faults import FaultInjector, FaultPlan, FaultSpec

        rng = np.random.default_rng(4)
        items = [(i, _discover(rng, b"\x02" + bytes(
            int(x) for x in rng.integers(0, 255, 5))))
            for i in range(24)]
        outs = {}
        for hp in ("scalar", "vector"):
            fl = _build_fleet(hp, False)
            plan = FaultPlan(specs=[FaultSpec(
                point="admission.admit", kind="force_shed", at_hit=5)])
            faults.arm(FaultInjector(plan))
            try:
                r = fl.handle_batch(list(items))
            finally:
                faults.disarm()
            outs[hp] = (r, fl.admission.stats_snapshot())
        # hit #5 shed by chaos in BOTH paths: the vector path detected
        # the armed plan and ran the per-frame oracle
        assert outs["scalar"] == outs["vector"]
        assert outs["scalar"][1]["shed"].get("chaos") == 1


# ---------------------------------------------------------------------------
# scheduler end-to-end A/B (compile-heavy: slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSchedulerExpressAB:
    def test_express_replies_identical(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        now = 1_753_000_000
        rng = np.random.default_rng(2)
        results = {}
        for hp in ("scalar", "vector"):
            prev = hostpath.HOST_PATH
            hostpath.HOST_PATH = hp
            try:
                fp = FastPathTables(sub_nbuckets=1 << 10,
                                    vlan_nbuckets=1 << 6,
                                    cid_nbuckets=1 << 6, max_pools=8)
                fp.set_server_config(bytes.fromhex("02aabbccdd01"),
                                     ip_to_u32("10.0.0.1"))
                fp.add_pool(1, ip_to_u32("10.0.0.0"), 16,
                            ip_to_u32("10.0.0.1"), ip_to_u32("1.1.1.1"),
                            ip_to_u32("8.8.8.8"), 86400)
                macs = []
                for i in range(64):
                    mac = (0x02AA00000000 + i).to_bytes(6, "big")
                    macs.append(mac)
                    fp.add_subscriber(mac, 1, ip_to_u32("10.0.1.0") + i,
                                      now + 86400)
                engine = Engine(fp, NATManager(public_ips=[0xCB007101]),
                                batch_size=64,
                                pkt_slot=512,
                                clock=lambda: float(now))
                sched = TieredScheduler(engine, SchedulerConfig(
                    express_batch=16), clock=lambda: float(now))
            finally:
                hostpath.HOST_PATH = prev
            frames = [_discover(rng, macs[i % 64]) for i in range(16)]
            rng = np.random.default_rng(2)  # same frames both cohorts
            frames = [_discover(rng, macs[i % 64]) for i in range(16)]
            out = sched.process(frames)
            results[hp] = sorted(out["tx"]), sorted(out["dropped"])
        assert results["scalar"] == results["vector"]
