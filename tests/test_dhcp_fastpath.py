"""DHCP fast-path kernel golden tests.

Packets are built with the host codec (bng_tpu.control), run through the
device kernel, and the reply bytes are decoded back with the independent
host parser — asserting the same externally-visible behavior as
dhcp_fastpath_prog (bpf/dhcp_fastpath.c:619-813).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.ops.dhcp import (
    NSTATS, ST_TOTAL, ST_HIT, ST_MISS, ST_ERROR, ST_EXPIRED,
    ST_OPT82_PRESENT, ST_BCAST, ST_UCAST, ST_VLAN,
    dhcp_fastpath,
)
from bng_tpu.ops.parse import parse_batch
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.utils.net import ip_to_u32, mac_to_u64

L = 512
B = 8

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")
BCAST_MAC = b"\xff" * 6
NOW = 1_700_000_000


def make_tables(**kw):
    t = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64, cid_nbuckets=64, max_pools=16, **kw)
    t.set_server_config(SERVER_MAC, SERVER_IP)
    t.add_pool(1, network=ip_to_u32("10.0.0.0"), prefix_len=24, gateway=ip_to_u32("10.0.0.1"),
               dns_primary=ip_to_u32("8.8.8.8"), dns_secondary=ip_to_u32("8.8.4.4"), lease_time=3600)
    return t


def dhcp_frame(mac, msg_type, vlans=None, giaddr=0, ciaddr=0, broadcast=False,
               circuit_id=b"", pad_before_53=0, src_ip=0):
    """Build a realistic client frame.

    Real clients pad the BOOTP payload (min 300 bytes; relayed packets are
    larger still) — the fast path, like the reference, requires 12 bytes of
    options for the msg-type scan (c:221) and a 64-byte window for the
    option-82 scan (c:276), so minimal unpadded packets go slow-path.
    """
    pkt = dhcp_codec.build_request(mac, msg_type, giaddr=giaddr, ciaddr=ciaddr,
                                   broadcast=broadcast, circuit_id=circuit_id)
    if not circuit_id:
        # typical client option-55 parameter request list (keeps option 82,
        # when present, directly after option 53 — the reference's position A)
        pkt.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 15, 51, 54])))
    if pad_before_53:
        pkt.options = [(dhcp_codec.OPT_PAD, b"")] * pad_before_53 + pkt.options
    payload = pkt.encode().ljust(320, b"\x00")
    return packets.udp_packet(
        src_mac=mac, dst_mac=BCAST_MAC, src_ip=src_ip, dst_ip=0xFFFFFFFF,
        src_port=68, dst_port=67, payload=payload, vlans=vlans,
    )


import functools
import jax


@functools.lru_cache(maxsize=4)
def _jitted(geom):
    @jax.jit
    def step(pkt, length, dev_tables, now):
        parsed = parse_batch(pkt, length)
        return dhcp_fastpath(pkt, length, parsed, dev_tables, geom, now)

    return step


def run_kernel(frames, tables):
    pkt = np.zeros((B, L), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    for i, f in enumerate(frames):
        pkt[i, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[i] = len(f)
    step = _jitted(tables.geom)
    return step(jnp.asarray(pkt), jnp.asarray(length), tables.device_tables(), jnp.uint32(NOW))


def reply_bytes(res, i):
    n = int(res.out_len[i])
    return bytes(np.asarray(res.out_pkt[i, :n], dtype=np.uint8))


class TestDiscoverOffer:
    def test_known_mac_gets_offer(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef01")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.50"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER)], t)
        assert bool(res.is_reply[0])
        raw = reply_bytes(res, 0)
        dec = packets.decode(raw)
        assert dec.dst_mac == BCAST_MAC  # DISCOVER w/o ciaddr -> broadcast (c:443-461)
        assert dec.src_mac == SERVER_MAC
        assert dec.src_ip == SERVER_IP and dec.dst_ip == 0xFFFFFFFF
        assert dec.ttl == 64 and dec.proto == 17
        assert dec.ip_checksum_ok, "IP header checksum must be valid"
        assert dec.src_port == 67 and dec.dst_port == 68
        assert dec.ip_total_len == len(raw) - 14
        d = dhcp_codec.decode(dec.payload)
        assert d.op == 2
        assert d.msg_type == dhcp_codec.OFFER
        assert d.yiaddr == ip_to_u32("10.0.0.50")
        assert d.siaddr == SERVER_IP
        assert d.chaddr[:6] == mac
        assert d.server_id == SERVER_IP
        assert d.opt(dhcp_codec.OPT_LEASE_TIME) == (3600).to_bytes(4, "big")
        assert d.opt(dhcp_codec.OPT_SUBNET_MASK) == bytes([255, 255, 255, 0])
        assert d.opt(dhcp_codec.OPT_ROUTER) == SERVER_IP.to_bytes(4, "big")
        assert d.opt(dhcp_codec.OPT_DNS) == ip_to_u32("8.8.8.8").to_bytes(4, "big") + ip_to_u32("8.8.4.4").to_bytes(4, "big")
        assert d.opt(dhcp_codec.OPT_RENEWAL_TIME) == (1800).to_bytes(4, "big")
        assert d.opt(dhcp_codec.OPT_REBIND_TIME) == (3150).to_bytes(4, "big")
        assert d.sname == b"" and d.file == b""
        st = np.asarray(res.stats)
        assert st[ST_TOTAL] == 1 and st[ST_HIT] == 1 and st[ST_MISS] == 0
        assert st[ST_BCAST] == 1 and st[ST_UCAST] == 0

    def test_request_gets_ack(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef02")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.51"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.REQUEST)], t)
        assert bool(res.is_reply[0])
        d = dhcp_codec.decode(packets.decode(reply_bytes(res, 0)).payload)
        assert d.msg_type == dhcp_codec.ACK
        assert d.yiaddr == ip_to_u32("10.0.0.51")

    def test_unknown_mac_passes(self):
        t = make_tables()
        res = run_kernel([dhcp_frame(bytes.fromhex("02000000aa01"), dhcp_codec.DISCOVER)], t)
        assert not bool(res.is_reply[0])
        assert bool(res.is_dhcp[0])
        st = np.asarray(res.stats)
        assert st[ST_MISS] == 1 and st[ST_HIT] == 0

    def test_expired_lease_passes(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef03")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.52"), lease_expiry=NOW - 1)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER)], t)
        assert not bool(res.is_reply[0])
        assert np.asarray(res.stats)[ST_EXPIRED] == 1

    def test_bad_pool_is_error(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef04")
        t.add_subscriber(mac, pool_id=9, ip=ip_to_u32("10.0.0.53"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER)], t)
        assert not bool(res.is_reply[0])
        assert np.asarray(res.stats)[ST_ERROR] == 1

    def test_non_dhcp_ignored(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef05")
        tcp = packets.tcp_packet(mac, SERVER_MAC, ip_to_u32("10.0.0.5"), ip_to_u32("1.1.1.1"), 1234, 80)
        udp = packets.udp_packet(mac, SERVER_MAC, ip_to_u32("10.0.0.5"), ip_to_u32("1.1.1.1"), 53, 53, b"x")
        res = run_kernel([tcp, udp], t)
        assert not bool(res.is_dhcp[0]) and not bool(res.is_dhcp[1])
        assert np.asarray(res.stats)[ST_TOTAL] == 0

    def test_release_passes_to_slow_path(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef06")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.54"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.RELEASE)], t)
        assert not bool(res.is_reply[0])
        assert np.asarray(res.stats)[ST_MISS] == 1  # wrong-type counted as miss (:643)


class TestMsgTypeOffsets:
    def test_pad_shifted_option53(self):
        """Option 53 after 1 pad byte is found (offset 1 checked, c:229)."""
        t = make_tables()
        mac = bytes.fromhex("02deadbeef07")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.55"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, pad_before_53=1)], t)
        assert bool(res.is_reply[0])

    def test_offset2_not_checked_passes(self):
        """Offset 2 is deliberately NOT in the reference's scan (c:224-246)."""
        t = make_tables()
        mac = bytes.fromhex("02deadbeef08")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.56"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, pad_before_53=2)], t)
        assert not bool(res.is_reply[0])  # slow path, like the reference


class TestVLAN:
    def test_single_tag_vlan_lookup_and_reply(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef09")
        t.add_vlan_subscriber(s_tag=100, c_tag=0, pool_id=1,
                              ip=ip_to_u32("10.0.0.60"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, vlans=[100])], t)
        assert bool(res.is_reply[0])
        dec = packets.decode(reply_bytes(res, 0))
        assert dec.vlans == [100], "VLAN tag must be preserved in reply"
        d = dhcp_codec.decode(dec.payload)
        assert d.yiaddr == ip_to_u32("10.0.0.60")
        assert np.asarray(res.stats)[ST_VLAN] == 1

    def test_qinq_lookup_and_reply(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0a")
        t.add_vlan_subscriber(s_tag=200, c_tag=31, pool_id=1,
                              ip=ip_to_u32("10.0.0.61"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, vlans=[200, 31])], t)
        assert bool(res.is_reply[0])
        dec = packets.decode(reply_bytes(res, 0))
        assert dec.vlans == [200, 31]
        assert dhcp_codec.decode(dec.payload).yiaddr == ip_to_u32("10.0.0.61")

    def test_vlan_miss_falls_back_to_mac(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0b")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.62"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, vlans=[999])], t)
        assert bool(res.is_reply[0])
        assert dhcp_codec.decode(packets.decode(reply_bytes(res, 0)).payload).yiaddr == ip_to_u32("10.0.0.62")


class TestOption82:
    def test_circuit_id_lookup(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0c")
        t.add_circuit_id_subscriber(b"olt1/slot2/port3", pool_id=1,
                                    ip=ip_to_u32("10.0.0.70"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER, circuit_id=b"olt1/slot2/port3")], t)
        assert bool(res.is_reply[0])
        d = dhcp_codec.decode(packets.decode(reply_bytes(res, 0)).payload)
        assert d.yiaddr == ip_to_u32("10.0.0.70")
        assert np.asarray(res.stats)[ST_OPT82_PRESENT] == 1


class TestRelayAndUnicast:
    def test_relayed_reply_unicast_to_giaddr(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0d")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.80"), lease_expiry=NOW + 600)
        relay_ip = ip_to_u32("10.9.9.9")
        frame = dhcp_frame(mac, dhcp_codec.DISCOVER, giaddr=relay_ip)
        res = run_kernel([frame], t)
        assert bool(res.is_reply[0])
        dec = packets.decode(reply_bytes(res, 0))
        assert dec.dst_mac == mac  # relay's MAC = requester frame's src MAC (:729)
        assert dec.dst_ip == relay_ip
        assert dec.src_port == 67 and dec.dst_port == 67  # :739-740
        assert dec.ip_checksum_ok
        d = dhcp_codec.decode(dec.payload)
        assert d.giaddr == relay_ip  # giaddr preserved

    def test_renewing_client_gets_l2_unicast(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0e")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.81"), lease_expiry=NOW + 600)
        frame = dhcp_frame(mac, dhcp_codec.REQUEST, ciaddr=ip_to_u32("10.0.0.81"),
                           src_ip=ip_to_u32("10.0.0.81"))
        res = run_kernel([frame], t)
        assert bool(res.is_reply[0])
        dec = packets.decode(reply_bytes(res, 0))
        assert dec.dst_mac == mac  # ciaddr set + no bcast flag -> unicast (:462)
        assert np.asarray(res.stats)[ST_UCAST] == 1

    def test_broadcast_flag_forces_broadcast(self):
        t = make_tables()
        mac = bytes.fromhex("02deadbeef0f")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.82"), lease_expiry=NOW + 600)
        frame = dhcp_frame(mac, dhcp_codec.REQUEST, ciaddr=ip_to_u32("10.0.0.82"),
                           broadcast=True, src_ip=ip_to_u32("10.0.0.82"))
        res = run_kernel([frame], t)
        dec = packets.decode(reply_bytes(res, 0))
        assert dec.dst_mac == BCAST_MAC


class TestDNSVariants:
    @pytest.mark.parametrize("dns1,dns2,expect", [
        (0, 0, None),
        (ip_to_u32("9.9.9.9"), 0, ip_to_u32("9.9.9.9").to_bytes(4, "big")),
        (ip_to_u32("9.9.9.9"), ip_to_u32("1.1.1.1"),
         ip_to_u32("9.9.9.9").to_bytes(4, "big") + ip_to_u32("1.1.1.1").to_bytes(4, "big")),
    ])
    def test_dns_layout(self, dns1, dns2, expect):
        t = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64, cid_nbuckets=64, max_pools=16)
        t.set_server_config(SERVER_MAC, SERVER_IP)
        t.add_pool(1, network=ip_to_u32("10.0.0.0"), prefix_len=24,
                   gateway=ip_to_u32("10.0.0.1"), dns_primary=dns1, dns_secondary=dns2,
                   lease_time=7200)
        mac = bytes.fromhex("02deadbe1f01")
        t.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.90"), lease_expiry=NOW + 600)
        res = run_kernel([dhcp_frame(mac, dhcp_codec.DISCOVER)], t)
        assert bool(res.is_reply[0])
        d = dhcp_codec.decode(packets.decode(reply_bytes(res, 0)).payload)
        assert d.opt(dhcp_codec.OPT_DNS) == expect
        # options after the DNS shift must still be intact
        assert d.opt(dhcp_codec.OPT_RENEWAL_TIME) == (3600).to_bytes(4, "big")
        assert d.opt(dhcp_codec.OPT_REBIND_TIME) == (6300).to_bytes(4, "big")


@pytest.mark.hotpath
class TestBatch:
    def test_mixed_batch(self):
        t = make_tables()
        known = bytes.fromhex("02deadbe2f01")
        t.add_subscriber(known, pool_id=1, ip=ip_to_u32("10.0.0.100"), lease_expiry=NOW + 600)
        frames = [
            dhcp_frame(known, dhcp_codec.DISCOVER),
            dhcp_frame(bytes.fromhex("020000000001"), dhcp_codec.DISCOVER),  # miss
            packets.tcp_packet(known, SERVER_MAC, ip_to_u32("10.0.0.5"), ip_to_u32("1.1.1.1"), 1, 2),
            dhcp_frame(known, dhcp_codec.REQUEST),
        ]
        res = run_kernel(frames, t)
        assert np.asarray(res.is_reply)[:4].tolist() == [True, False, False, True]
        st = np.asarray(res.stats)
        assert st[ST_TOTAL] == 3 and st[ST_HIT] == 2 and st[ST_MISS] == 1
