"""Tests for the routing package: platform, manager, BGP, BFD, sub routes."""

import json

import pytest

from bng_tpu.control.routing import (
    BFDManager, BFDState, BGPAnnouncement, BGPConfig, BGPController,
    BGPNeighbor, BGPState, LinkState, NextHop, PolicyRule, Route,
    RoutingConfig, RoutingManager, StubPlatform, SubscriberRoute,
    SubscriberRouteConfig, SubscriberRouteManager, aggressive_bfd_config,
    parse_bgp_state,
)


class RecordingFRR:
    """Records commands; canned JSON per 'show' command."""

    def __init__(self):
        self.commands = []
        self.responses = {}
        self.fail_next = 0

    def __call__(self, command):
        if self.fail_next:
            self.fail_next -= 1
            raise RuntimeError("vtysh failed")
        self.commands.append(command)
        for key, resp in self.responses.items():
            if command.startswith(key):
                return resp
        return ""

    def all_text(self):
        return "\n".join(self.commands)


# ----------------------------------------------------------- platform

class TestStubPlatform:
    def test_route_crud(self):
        p = StubPlatform()
        r = Route(destination="10.0.0.0/24", gateway="192.168.1.1", table=100)
        p.add_route(r)
        assert p.get_routes(100) == [r]
        with pytest.raises(FileExistsError):
            p.add_route(r)
        p.delete_route(r)
        assert p.get_routes(100) == []
        with pytest.raises(FileNotFoundError):
            p.delete_route(r)

    def test_rules_sorted_by_priority(self):
        p = StubPlatform()
        p.add_rule(PolicyRule(priority=200, table=2))
        p.add_rule(PolicyRule(priority=100, table=1))
        assert [r.priority for r in p.get_rules()] == [100, 200]

    def test_ping(self):
        p = StubPlatform()
        p.reachable["8.8.8.8"] = 0.01
        assert p.ping("8.8.8.8") == 0.01
        with pytest.raises(TimeoutError):
            p.ping("1.2.3.4")


# ------------------------------------------------------------ manager

class TestRoutingManager:
    def test_isp_table_and_subscriber_steering(self):
        m = RoutingManager()
        m.add_upstream_table = None
        m.create_isp_table("isp-a", 100, "192.168.1.1", "eth1")
        assert m.platform.get_routes(100)[0].gateway == "192.168.1.1"
        rule = m.route_subscriber_to_isp("10.5.0.9", 100)
        assert rule.src == "10.5.0.9/32" and rule.table == 100
        m.unroute_subscriber("10.5.0.9", 100)
        assert m.platform.get_rules() == []

    def test_upstream_add_creates_table(self):
        from bng_tpu.control.routing import Upstream
        m = RoutingManager()
        m.add_upstream(Upstream(name="isp-a", interface="eth1",
                                gateway="192.168.1.1", table=100))
        assert m.platform.get_routes(100)
        m.remove_upstream("isp-a")
        assert m.platform.get_routes(100) == []

    def test_ecmp_default_gateway(self):
        m = RoutingManager()
        m.set_default_gateway_ecmp([NextHop("192.168.1.1", "eth1"),
                                    NextHop("192.168.2.1", "eth2")])
        r = m.platform.get_routes(254)[0]
        assert len(r.nexthops) == 2

    def test_health_check_failover_and_recovery(self):
        from bng_tpu.control.routing import Upstream
        m = RoutingManager(RoutingConfig(failure_threshold=2))
        events = []
        m.on_upstream_down = lambda n: events.append(("down", n))
        m.on_upstream_up = lambda n: events.append(("up", n))
        m.add_upstream(Upstream(name="isp-a", health_target="1.1.1.1"))
        m.platform.reachable["1.1.1.1"] = 0.005
        m.check_health()
        assert m.get_upstream("isp-a").state == LinkState.UP
        del m.platform.reachable["1.1.1.1"]
        m.check_health()  # 1st failure: still UP
        assert m.get_upstream("isp-a").state == LinkState.UP
        m.check_health()  # 2nd failure: DOWN
        assert m.get_upstream("isp-a").state == LinkState.DOWN
        m.platform.reachable["1.1.1.1"] = 0.005
        m.check_health()
        assert events == [("up", "isp-a"), ("down", "isp-a"), ("up", "isp-a")]
        assert m.routing_stats()["failovers"] == 1


# ---------------------------------------------------------------- BGP

class TestBGP:
    def test_add_neighbor_emits_frr_config(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(local_as=65001), frr)
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002,
                                   description="upstream-a", bfd_enabled=True,
                                   next_hop_self=True))
        text = frr.all_text()
        assert "router bgp 65001" in text
        assert "neighbor 10.0.0.2 remote-as 65002" in text
        assert "neighbor 10.0.0.2 bfd" in text
        assert "neighbor 10.0.0.2 next-hop-self" in text
        with pytest.raises(ValueError):
            b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=1))

    def test_announce_withdraw(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(), frr)
        b.announce_prefix("203.0.113.0/24")
        assert "network 203.0.113.0/24" in frr.all_text()
        assert len(b.list_announcements()) == 1
        b.withdraw_prefix("203.0.113.0/24")
        assert "no network 203.0.113.0/24" in frr.all_text()
        assert b.list_announcements() == []
        with pytest.raises(ValueError):
            b.announce_prefix("not-a-prefix")

    def test_refresh_fires_callbacks(self):
        frr = RecordingFRR()
        frr.responses["show bgp"] = json.dumps({
            "peers": {"10.0.0.2": {"state": "Established", "pfxRcd": 42}}})
        b = BGPController(BGPConfig(), frr)
        ups, downs = [], []
        b.on_neighbor_up = ups.append
        b.on_neighbor_down = downs.append
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002))
        b.refresh_neighbors()
        assert ups == ["10.0.0.2"]
        n = b.get_neighbor("10.0.0.2")
        assert n.state == BGPState.ESTABLISHED and n.prefixes_received == 42
        frr.responses["show bgp"] = json.dumps({
            "peers": {"10.0.0.2": {"state": "Active"}}})
        b.refresh_neighbors()
        assert downs == ["10.0.0.2"]
        assert b.summary()["established"] == 0

    def test_generate_config(self):
        b = BGPController(BGPConfig(local_as=65001, router_id="10.0.0.1"),
                          RecordingFRR())
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002,
                                   route_map_out="EXPORT"))
        b.announce_prefix("203.0.113.0/24")
        cfg = b.generate_config()
        assert "router bgp 65001" in cfg
        assert " bgp router-id 10.0.0.1" in cfg
        assert "  network 203.0.113.0/24" in cfg
        assert "  neighbor 10.0.0.2 route-map EXPORT out" in cfg

    def test_route_map_and_max_paths(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(), frr)
        b.create_route_map("EXPORT", 10, "permit",
                           match_clauses=["ip address prefix-list SUBS"],
                           set_clauses=["community 65000:100"])
        b.enable_max_paths(4)
        text = frr.all_text()
        assert "route-map EXPORT permit 10" in text
        assert "set community 65000:100" in text
        assert "maximum-paths 4" in text
        with pytest.raises(ValueError):
            b.enable_max_paths(0)

    def test_parse_state(self):
        assert parse_bgp_state("established") == BGPState.ESTABLISHED
        assert parse_bgp_state("garbage") == BGPState.IDLE


# ---------------------------------------------------------------- BFD

class TestBFD:
    def test_peer_lifecycle(self):
        frr = RecordingFRR()
        m = BFDManager(executor=frr)
        p = m.add_peer("10.0.0.2")
        assert p.min_rx_ms == 300
        assert "peer 10.0.0.2" in frr.all_text()
        with pytest.raises(ValueError):
            m.add_peer("10.0.0.2")
        m.remove_peer("10.0.0.2")
        assert "no peer 10.0.0.2" in frr.all_text()

    def test_aggressive_profile(self):
        cfg = aggressive_bfd_config()
        m = BFDManager(cfg, executor=RecordingFRR())
        assert m.add_peer("10.0.0.3").min_rx_ms == 50

    def test_link_to_bgp(self):
        frr = RecordingFRR()
        m = BFDManager(executor=frr)
        m.link_to_bgp_neighbor(65001, "10.0.0.2")
        assert m.get_peer("10.0.0.2").linked_bgp_as == 65001
        assert "neighbor 10.0.0.2 bfd" in frr.all_text()

    def test_refresh_transitions(self):
        frr = RecordingFRR()
        frr.responses["show bfd"] = json.dumps(
            [{"peer": "10.0.0.2", "status": "up"}])
        m = BFDManager(executor=frr)
        ups, downs = [], []
        m.on_peer_up = ups.append
        m.on_peer_down = downs.append
        m.add_peer("10.0.0.2")
        m.refresh_peers()
        assert ups == ["10.0.0.2"]
        assert m.bfd_stats() == {"peers": 1, "up": 1}
        frr.responses["show bfd"] = json.dumps(
            [{"peer": "10.0.0.2", "status": "down"}])
        m.refresh_peers()
        assert downs == ["10.0.0.2"]


# -------------------------------------------------- subscriber routes

class TestSubscriberRoutes:
    def test_inject_with_class_community(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        r = m.inject_route("sess-1", "sub-1", "100.64.0.5", "business")
        assert r.community == "65000:200"
        assert "ip route 100.64.0.5/32" in frr.all_text()
        assert m.get_route_by_ip("100.64.0.5").session_id == "sess-1"

    def test_unknown_class_gets_default(self):
        m = SubscriberRouteManager(executor=RecordingFRR())
        r = m.inject_route("s", "x", "100.64.0.6", "mystery")
        assert r.community == "65000:100"

    def test_withdraw(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        m.inject_route("sess-1", "sub-1", "100.64.0.5")
        m.withdraw_route("sess-1")
        assert "no ip route 100.64.0.5/32" in frr.all_text()
        assert m.get_active_routes() == []
        with pytest.raises(KeyError):
            m.withdraw_route("sess-1")

    def test_bulk_ops_single_session(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        routes = [SubscriberRoute(session_id=f"s{i}", subscriber_id=f"u{i}",
                                  ip=f"100.64.1.{i}") for i in range(5)]
        assert m.bulk_inject(routes) == 5
        assert len(frr.commands) == 1  # one config session
        assert m.bulk_withdraw() == 5
        assert m.route_stats()["active"] == 0

    def test_retry_queue(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        frr.fail_next = 1
        with pytest.raises(RuntimeError):
            m.inject_route("sess-1", "sub-1", "100.64.0.5")
        assert m.route_stats()["failed"] == 1
        assert m.retry_pending() == 1
        assert m.get_route_by_ip("100.64.0.5") is not None
        assert m.route_stats()["retried"] == 1

    def test_invalid_ip_rejected(self):
        m = SubscriberRouteManager(executor=RecordingFRR())
        with pytest.raises(ValueError):
            m.inject_route("s", "u", "not-an-ip")
