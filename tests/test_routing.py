"""Tests for the routing package: platform, manager, BGP, BFD, sub routes."""

import json

import pytest

from bng_tpu.control.routing import (
    BFDManager, BFDState, BGPAnnouncement, BGPConfig, BGPController,
    BGPNeighbor, BGPState, LinkState, NextHop, PolicyRule, Route,
    RoutingConfig, RoutingManager, StubPlatform, SubscriberRoute,
    SubscriberRouteConfig, SubscriberRouteManager, aggressive_bfd_config,
    parse_bgp_state,
)


class RecordingFRR:
    """Records commands; canned JSON per 'show' command."""

    def __init__(self):
        self.commands = []
        self.responses = {}
        self.fail_next = 0

    def __call__(self, command):
        if self.fail_next:
            self.fail_next -= 1
            raise RuntimeError("vtysh failed")
        self.commands.append(command)
        for key, resp in self.responses.items():
            if command.startswith(key):
                return resp
        return ""

    def all_text(self):
        return "\n".join(self.commands)


# ----------------------------------------------------------- platform

class TestStubPlatform:
    def test_route_crud(self):
        p = StubPlatform()
        r = Route(destination="10.0.0.0/24", gateway="192.168.1.1", table=100)
        p.add_route(r)
        assert p.get_routes(100) == [r]
        with pytest.raises(FileExistsError):
            p.add_route(r)
        p.delete_route(r)
        assert p.get_routes(100) == []
        with pytest.raises(FileNotFoundError):
            p.delete_route(r)

    def test_rules_sorted_by_priority(self):
        p = StubPlatform()
        p.add_rule(PolicyRule(priority=200, table=2))
        p.add_rule(PolicyRule(priority=100, table=1))
        assert [r.priority for r in p.get_rules()] == [100, 200]

    def test_ping(self):
        p = StubPlatform()
        p.reachable["8.8.8.8"] = 0.01
        assert p.ping("8.8.8.8") == 0.01
        with pytest.raises(TimeoutError):
            p.ping("1.2.3.4")


# ------------------------------------------------------------ manager

class TestRoutingManager:
    def test_isp_table_and_subscriber_steering(self):
        m = RoutingManager()
        m.add_upstream_table = None
        m.create_isp_table("isp-a", 100, "192.168.1.1", "eth1")
        assert m.platform.get_routes(100)[0].gateway == "192.168.1.1"
        rule = m.route_subscriber_to_isp("10.5.0.9", 100)
        assert rule.src == "10.5.0.9/32" and rule.table == 100
        m.unroute_subscriber("10.5.0.9", 100)
        assert m.platform.get_rules() == []

    def test_upstream_add_creates_table(self):
        from bng_tpu.control.routing import Upstream
        m = RoutingManager()
        m.add_upstream(Upstream(name="isp-a", interface="eth1",
                                gateway="192.168.1.1", table=100))
        assert m.platform.get_routes(100)
        m.remove_upstream("isp-a")
        assert m.platform.get_routes(100) == []

    def test_ecmp_default_gateway(self):
        m = RoutingManager()
        m.set_default_gateway_ecmp([NextHop("192.168.1.1", "eth1"),
                                    NextHop("192.168.2.1", "eth2")])
        r = m.platform.get_routes(254)[0]
        assert len(r.nexthops) == 2

    def test_health_check_failover_and_recovery(self):
        from bng_tpu.control.routing import Upstream
        m = RoutingManager(RoutingConfig(failure_threshold=2))
        events = []
        m.on_upstream_down = lambda n: events.append(("down", n))
        m.on_upstream_up = lambda n: events.append(("up", n))
        m.add_upstream(Upstream(name="isp-a", health_target="1.1.1.1"))
        m.platform.reachable["1.1.1.1"] = 0.005
        m.check_health()
        assert m.get_upstream("isp-a").state == LinkState.UP
        del m.platform.reachable["1.1.1.1"]
        m.check_health()  # 1st failure: still UP
        assert m.get_upstream("isp-a").state == LinkState.UP
        m.check_health()  # 2nd failure: DOWN
        assert m.get_upstream("isp-a").state == LinkState.DOWN
        m.platform.reachable["1.1.1.1"] = 0.005
        m.check_health()
        assert events == [("up", "isp-a"), ("down", "isp-a"), ("up", "isp-a")]
        assert m.routing_stats()["failovers"] == 1


# ---------------------------------------------------------------- BGP

class TestBGP:
    def test_add_neighbor_emits_frr_config(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(local_as=65001), frr)
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002,
                                   description="upstream-a", bfd_enabled=True,
                                   next_hop_self=True))
        text = frr.all_text()
        assert "router bgp 65001" in text
        assert "neighbor 10.0.0.2 remote-as 65002" in text
        assert "neighbor 10.0.0.2 bfd" in text
        assert "neighbor 10.0.0.2 next-hop-self" in text
        with pytest.raises(ValueError):
            b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=1))

    def test_announce_withdraw(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(), frr)
        b.announce_prefix("203.0.113.0/24")
        assert "network 203.0.113.0/24" in frr.all_text()
        assert len(b.list_announcements()) == 1
        b.withdraw_prefix("203.0.113.0/24")
        assert "no network 203.0.113.0/24" in frr.all_text()
        assert b.list_announcements() == []
        with pytest.raises(ValueError):
            b.announce_prefix("not-a-prefix")

    def test_refresh_fires_callbacks(self):
        frr = RecordingFRR()
        frr.responses["show bgp"] = json.dumps({
            "peers": {"10.0.0.2": {"state": "Established", "pfxRcd": 42}}})
        b = BGPController(BGPConfig(), frr)
        ups, downs = [], []
        b.on_neighbor_up = ups.append
        b.on_neighbor_down = downs.append
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002))
        b.refresh_neighbors()
        assert ups == ["10.0.0.2"]
        n = b.get_neighbor("10.0.0.2")
        assert n.state == BGPState.ESTABLISHED and n.prefixes_received == 42
        frr.responses["show bgp"] = json.dumps({
            "peers": {"10.0.0.2": {"state": "Active"}}})
        b.refresh_neighbors()
        assert downs == ["10.0.0.2"]
        assert b.summary()["established"] == 0

    def test_generate_config(self):
        b = BGPController(BGPConfig(local_as=65001, router_id="10.0.0.1"),
                          RecordingFRR())
        b.add_neighbor(BGPNeighbor(address="10.0.0.2", remote_as=65002,
                                   route_map_out="EXPORT"))
        b.announce_prefix("203.0.113.0/24")
        cfg = b.generate_config()
        assert "router bgp 65001" in cfg
        assert " bgp router-id 10.0.0.1" in cfg
        assert "  network 203.0.113.0/24" in cfg
        assert "  neighbor 10.0.0.2 route-map EXPORT out" in cfg

    def test_route_map_and_max_paths(self):
        frr = RecordingFRR()
        b = BGPController(BGPConfig(), frr)
        b.create_route_map("EXPORT", 10, "permit",
                           match_clauses=["ip address prefix-list SUBS"],
                           set_clauses=["community 65000:100"])
        b.enable_max_paths(4)
        text = frr.all_text()
        assert "route-map EXPORT permit 10" in text
        assert "set community 65000:100" in text
        assert "maximum-paths 4" in text
        with pytest.raises(ValueError):
            b.enable_max_paths(0)

    def test_parse_state(self):
        assert parse_bgp_state("established") == BGPState.ESTABLISHED
        assert parse_bgp_state("garbage") == BGPState.IDLE


# ---------------------------------------------------------------- BFD

class TestBFD:
    def test_peer_lifecycle(self):
        frr = RecordingFRR()
        m = BFDManager(executor=frr)
        p = m.add_peer("10.0.0.2")
        assert p.min_rx_ms == 300
        assert "peer 10.0.0.2" in frr.all_text()
        with pytest.raises(ValueError):
            m.add_peer("10.0.0.2")
        m.remove_peer("10.0.0.2")
        assert "no peer 10.0.0.2" in frr.all_text()

    def test_aggressive_profile(self):
        cfg = aggressive_bfd_config()
        m = BFDManager(cfg, executor=RecordingFRR())
        assert m.add_peer("10.0.0.3").min_rx_ms == 50

    def test_link_to_bgp(self):
        frr = RecordingFRR()
        m = BFDManager(executor=frr)
        m.link_to_bgp_neighbor(65001, "10.0.0.2")
        assert m.get_peer("10.0.0.2").linked_bgp_as == 65001
        assert "neighbor 10.0.0.2 bfd" in frr.all_text()

    def test_refresh_transitions(self):
        frr = RecordingFRR()
        frr.responses["show bfd"] = json.dumps(
            [{"peer": "10.0.0.2", "status": "up"}])
        m = BFDManager(executor=frr)
        ups, downs = [], []
        m.on_peer_up = ups.append
        m.on_peer_down = downs.append
        m.add_peer("10.0.0.2")
        m.refresh_peers()
        assert ups == ["10.0.0.2"]
        assert m.bfd_stats() == {"peers": 1, "up": 1}
        frr.responses["show bfd"] = json.dumps(
            [{"peer": "10.0.0.2", "status": "down"}])
        m.refresh_peers()
        assert downs == ["10.0.0.2"]


# -------------------------------------------------- subscriber routes

class TestSubscriberRoutes:
    def test_inject_with_class_community(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        r = m.inject_route("sess-1", "sub-1", "100.64.0.5", "business")
        assert r.community == "65000:200"
        assert "ip route 100.64.0.5/32" in frr.all_text()
        assert m.get_route_by_ip("100.64.0.5").session_id == "sess-1"

    def test_unknown_class_gets_default(self):
        m = SubscriberRouteManager(executor=RecordingFRR())
        r = m.inject_route("s", "x", "100.64.0.6", "mystery")
        assert r.community == "65000:100"

    def test_withdraw(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        m.inject_route("sess-1", "sub-1", "100.64.0.5")
        m.withdraw_route("sess-1")
        assert "no ip route 100.64.0.5/32" in frr.all_text()
        assert m.get_active_routes() == []
        with pytest.raises(KeyError):
            m.withdraw_route("sess-1")

    def test_bulk_ops_single_session(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        routes = [SubscriberRoute(session_id=f"s{i}", subscriber_id=f"u{i}",
                                  ip=f"100.64.1.{i}") for i in range(5)]
        assert m.bulk_inject(routes) == 5
        assert len(frr.commands) == 1  # one config session
        assert m.bulk_withdraw() == 5
        assert m.route_stats()["active"] == 0

    def test_retry_queue(self):
        frr = RecordingFRR()
        m = SubscriberRouteManager(executor=frr)
        frr.fail_next = 1
        with pytest.raises(RuntimeError):
            m.inject_route("sess-1", "sub-1", "100.64.0.5")
        assert m.route_stats()["failed"] == 1
        assert m.retry_pending() == 1
        assert m.get_route_by_ip("100.64.0.5") is not None
        assert m.route_stats()["retried"] == 1

    def test_invalid_ip_rejected(self):
        m = SubscriberRouteManager(executor=RecordingFRR())
        with pytest.raises(ValueError):
            m.inject_route("s", "u", "not-an-ip")


# ---------------------------------------------------------------------------
# Real-world wiring (VERDICT r3 item 5): vtysh executor + Linux platform
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self, stdout="", stderr="", returncode=0):
        self.stdout, self.stderr, self.returncode = stdout, stderr, returncode


class TestVtyshExecutor:
    """Parity: bgp.go:554-578 — one -c per config line."""

    def test_multiline_config_becomes_dash_c_chain(self):
        from bng_tpu.control.routing import vtysh_executor

        calls = []
        ex = vtysh_executor(binary="/usr/bin/vtysh",
                           runner=lambda a: (calls.append(a), _FakeProc("ok"))[1])
        out = ex("configure terminal\nrouter bgp 65001\nneighbor 1.2.3.4 remote-as 65002")
        assert out == "ok"
        assert calls == [[
            "/usr/bin/vtysh",
            "-c", "configure terminal",
            "-c", "router bgp 65001",
            "-c", "neighbor 1.2.3.4 remote-as 65002",
        ]]

    def test_nonzero_rc_raises(self):
        from bng_tpu.control.routing import vtysh_executor

        ex = vtysh_executor(runner=lambda a: _FakeProc(stderr="% Unknown command", returncode=1))
        with pytest.raises(RuntimeError, match="Unknown command"):
            ex("show bgp summary")

    def test_bgp_controller_through_vtysh_executor(self):
        """BGPController -> vtysh_executor end-to-end: real FRR argv."""
        from bng_tpu.control.routing import (BGPConfig, BGPController,
                                             BGPNeighbor, vtysh_executor)

        calls = []
        ctl = BGPController(
            BGPConfig(local_as=65001, router_id="10.0.0.1"),
            executor=vtysh_executor(
                runner=lambda a: (calls.append(a), _FakeProc())[1]))
        ctl.add_neighbor(BGPNeighbor(address="192.0.2.1", remote_as=65002))
        ctl.announce_prefix("198.51.100.0/24")
        flat = [" ".join(c) for c in calls]
        assert any("router bgp 65001" in f and "remote-as 65002" in f for f in flat)
        assert any("network 198.51.100.0/24" in f for f in flat)


class TestIPRoute2Hermetic:
    """IPRoute2Platform with an injected runner: exact ip(8) argv + JSON
    parsing, no kernel required (the _stub.go-style hermetic layer)."""

    def _platform(self, outputs=None):
        from bng_tpu.control.routing import IPRoute2Platform

        calls = []
        outputs = dict(outputs or {})

        def runner(args):
            calls.append(args)
            key = " ".join(args[1:])
            out = outputs.get(key, "")
            return _FakeProc(stdout=out)

        return IPRoute2Platform(runner=runner), calls

    def test_add_route_argv(self):
        p, calls = self._platform()
        p.add_route(Route(destination="10.1.0.0/16", gateway="192.168.0.1",
                          interface="eth1", table=101, metric=50))
        assert calls == [["ip", "route", "add", "10.1.0.0/16", "table", "101",
                          "via", "192.168.0.1", "dev", "eth1", "metric", "50"]]

    def test_ecmp_route_argv(self):
        from bng_tpu.control.routing import NextHop

        p, calls = self._platform()
        p.add_route(Route(destination="0.0.0.0/0", table=254, nexthops=(
            NextHop(gateway="10.0.0.1", interface="eth1", weight=2),
            NextHop(gateway="10.0.1.1", interface="eth2", weight=1))))
        assert calls[0] == ["ip", "route", "add", "0.0.0.0/0", "table", "254",
                            "nexthop", "via", "10.0.0.1", "dev", "eth1",
                            "weight", "2",
                            "nexthop", "via", "10.0.1.1", "dev", "eth2",
                            "weight", "1"]

    def test_get_routes_parses_json(self):
        routes_json = ('[{"dst":"default","gateway":"10.0.0.1","dev":"eth1",'
                       '"metric":100},'
                       '{"dst":"192.0.2.5","dev":"lo"},'
                       '{"dst":"10.2.0.0/16","nexthops":[{"gateway":"10.0.0.1",'
                       '"dev":"eth1","weight":2},{"gateway":"10.0.1.1",'
                       '"dev":"eth2","weight":1}]}]')
        p, _ = self._platform({"-j route show table 101": routes_json})
        got = p.get_routes(101)
        assert got[0].destination == "0.0.0.0/0" and got[0].metric == 100
        assert got[1].destination == "192.0.2.5/32"
        assert [n.weight for n in got[2].nexthops] == [2, 1]

    def test_file_exists_maps_to_contract_error(self):
        from bng_tpu.control.routing import IPRoute2Platform

        p = IPRoute2Platform(runner=lambda a: _FakeProc(
            stderr="RTNETLINK answers: File exists", returncode=2))
        with pytest.raises(FileExistsError):
            p.add_route(Route(destination="10.0.0.0/24", table=100))

    def test_rules_parse_and_duplicate_contract(self):
        rules_json = ('[{"priority":0,"src":"all","table":"local"},'
                      '{"priority":15000,"src":"10.99.0.0","srclen":24,'
                      '"table":"101"},'
                      '{"priority":32766,"src":"all","table":"main"}]')
        p, calls = self._platform({"-j rule show": rules_json})
        rules = p.get_rules()
        assert rules == [PolicyRule(priority=15000, table=101,
                                    src="10.99.0.0/24")]
        # duplicate contract rides the kernel's own EEXIST (no pre-scan)
        from bng_tpu.control.routing import IPRoute2Platform

        dup = IPRoute2Platform(runner=lambda a: _FakeProc(
            stderr="RTNETLINK answers: File exists", returncode=2))
        with pytest.raises(FileExistsError):
            dup.add_rule(PolicyRule(priority=15000, table=101,
                                    src="10.99.0.0/24"))


def _have_net_admin() -> bool:
    import subprocess

    try:
        r = subprocess.run(["ip", "route", "add", "192.0.2.254/32", "dev",
                            "lo", "table", "19999"], capture_output=True)
        if r.returncode != 0:
            return False
        subprocess.run(["ip", "route", "flush", "table", "19999"],
                       capture_output=True)
        return True
    except OSError:
        return False


NET_ADMIN = _have_net_admin()


@pytest.mark.skipif(not NET_ADMIN, reason="needs CAP_NET_ADMIN + iproute2")
class TestIPRoute2Kernel:
    """The adapter passes the StubPlatform contract against the REAL
    kernel (netlink_linux.go:20-442 role). Uses dedicated table/priority
    numbers and cleans up after itself."""

    TABLE = 19998

    @pytest.fixture
    def p(self):
        from bng_tpu.control.routing import IPRoute2Platform

        plat = IPRoute2Platform()
        yield plat
        plat.flush_table(self.TABLE)
        for r in plat.get_rules():
            if r.table == self.TABLE:
                plat.delete_rule(r)

    def test_route_crud_contract(self, p):
        r = Route(destination="192.0.2.0/24", interface="lo", table=self.TABLE)
        p.add_route(r)
        got = p.get_routes(self.TABLE)
        assert len(got) == 1
        assert got[0].destination == "192.0.2.0/24"
        assert got[0].interface == "lo"
        with pytest.raises(FileExistsError):
            p.add_route(r)
        p.delete_route(r)
        assert p.get_routes(self.TABLE) == []

    def test_ecmp_route_in_kernel(self, p):
        import subprocess

        subprocess.run(["ip", "link", "add", "bngr0", "type", "veth",
                        "peer", "name", "bngr1"], capture_output=True)
        try:
            p.set_interface_up("bngr0")
            p.set_interface_up("bngr1")
            r = Route(destination="198.51.100.0/24", table=self.TABLE,
                      nexthops=(NextHop(gateway="", interface="bngr0",
                                        weight=2),
                                NextHop(gateway="", interface="bngr1",
                                        weight=1)))
            p.add_route(r)
            got = p.get_routes(self.TABLE)
            assert len(got) == 1
            assert sorted(n.interface for n in got[0].nexthops) == \
                ["bngr0", "bngr1"]
        finally:
            subprocess.run(["ip", "link", "del", "bngr0"], capture_output=True)

    def test_policy_rule_contract(self, p):
        rule = PolicyRule(priority=19998, table=self.TABLE, src="10.98.0.0/24")
        p.add_rule(rule)
        assert rule in p.get_rules()
        with pytest.raises(FileExistsError):
            p.add_rule(rule)
        p.delete_rule(rule)
        assert rule not in p.get_rules()
        with pytest.raises(FileNotFoundError):
            p.delete_rule(rule)

    def test_interface_and_updown(self, p):
        lo = p.get_interface("lo")
        assert lo.index == 1 and lo.up
        with pytest.raises(FileNotFoundError):
            p.get_interface("bng-does-not-exist")

    def test_routing_manager_on_real_kernel(self, p):
        """Multi-ISP steering end-to-end against the kernel: ISP table +
        subscriber policy rule actually land in ip route/ip rule."""
        from bng_tpu.control.routing import RoutingManager

        m = RoutingManager(platform=p)
        m.create_isp_table("ispA", self.TABLE, gateway="", interface="lo")
        m.route_subscriber_to_isp("10.98.0.77", self.TABLE, priority=19998)
        assert any(r.table == self.TABLE for r in p.get_rules())
        m.unroute_subscriber("10.98.0.77", self.TABLE, priority=19998)
        assert not any(r.table == self.TABLE for r in p.get_rules())

    def test_raw_icmp_ping_loopback(self, p):
        try:
            rtt = p.ping("127.0.0.1", timeout=2.0)
        except TimeoutError:
            pytest.skip("no ICMP capability in sandbox")
        assert 0 <= rtt < 2.0
        with pytest.raises(TimeoutError):
            p.ping("192.0.2.123", timeout=0.3)  # TEST-NET: no reply


class TestCLIVtyshWiring:
    """`run` with BGP flags emits real vtysh commands (VERDICT r3 item 5
    done-criterion), proven through an executor-logging fake vtysh."""

    def test_bgp_flags_drive_vtysh_subprocess(self, tmp_path):
        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.control.routing import BGPNeighbor

        log = tmp_path / "vtysh.log"
        fake = tmp_path / "vtysh"
        fake.write_text("#!/bin/sh\necho \"$@\" >> " + str(log) + "\n")
        fake.chmod(0o755)
        app = BNGApp(BNGConfig(bgp_enabled=True, bgp_vtysh=True,
                               bgp_vtysh_path=str(fake),
                               bgp_local_as=65010))
        try:
            app.components["bgp"].add_neighbor(
                BGPNeighbor(address="192.0.2.9", remote_as=65020))
        finally:
            app.close()
        logged = log.read_text()
        assert "router bgp 65010" in logged
        assert "neighbor 192.0.2.9 remote-as 65020" in logged

    def test_linux_platform_flag(self):
        from bng_tpu.cli import BNGApp, BNGConfig

        if not NET_ADMIN:
            pytest.skip("needs CAP_NET_ADMIN")
        app = BNGApp(BNGConfig(routing_platform="linux"))
        try:
            assert "routing" in app.components
            lo = app.components["routing"].platform.get_interface("lo")
            assert lo.index == 1
        finally:
            app.close()

    def test_bulk_config_chunks_under_arg_max(self):
        """A 1M-scale bulk inject/withdraw must not build one giant argv
        (execve E2BIG); chunks re-enter config mode (review r4)."""
        from bng_tpu.control.routing import vtysh_executor

        calls = []
        ex = vtysh_executor(runner=lambda a: (calls.append(a), _FakeProc())[1])
        lines = ["configure terminal", "router bgp 65001"] + [
            f"network 10.{i >> 8 & 255}.{i & 255}.0/32" for i in range(1000)]
        ex("\n".join(lines))
        assert len(calls) > 1  # chunked
        for c in calls:
            assert len(c) < 2 * 450  # bounded argv
            # every chunk is a complete session: preamble present
            assert c[1:5] == ["-c", "configure terminal", "-c",
                              "router bgp 65001"]
        # all 1000 lines delivered exactly once
        delivered = [x for call in calls for x in call[2::2]
                     if x.startswith("network ")]
        assert len(delivered) == 1000 and len(set(delivered)) == 1000

    def test_chunk_boundary_reenters_current_context(self):
        """Advisor r5: a multi-section config crossing the chunk boundary
        must replay the CURRENT context (the second router block), not the
        first chunk's preamble — or later lines would apply to the wrong
        router/address-family."""
        from bng_tpu.control.routing import vtysh_executor

        calls = []
        ex = vtysh_executor(runner=lambda a: (calls.append(a), _FakeProc())[1])
        lines = (["configure terminal", "router bgp 65001"]
                 + [f"network 10.0.{i & 255}.0/32" for i in range(200)]
                 + ["exit", "router bgp 65002",
                    "address-family ipv6 unicast"]
                 + [f"network 2001:db8:{i:x}::/48" for i in range(300)])
        ex("\n".join(lines))
        assert len(calls) > 1
        # the chunk containing the tail v6 networks re-enters bgp 65002 +
        # the v6 address-family, NOT bgp 65001
        last = calls[-1][2::2]  # the -c arguments
        assert last[0] == "configure terminal"
        assert last[1] == "router bgp 65002"
        assert last[2] == "address-family ipv6 unicast"
        assert "router bgp 65001" not in last
        # nothing lost, nothing duplicated
        delivered = [x for call in calls for x in call[2::2]
                     if x.startswith("network ")]
        assert len(delivered) == 500 and len(set(delivered)) == 500

    def test_sibling_stanzas_bound_the_replay_stack(self):
        """Review r5: consecutive `interface X` stanzas carry no `exit`
        (vtysh switches context implicitly) — the replay stack must stay
        bounded, not accumulate every sibling into each chunk preamble."""
        from bng_tpu.control.routing import vtysh_executor

        calls = []
        ex = vtysh_executor(runner=lambda a: (calls.append(a), _FakeProc())[1])
        lines = ["configure terminal"]
        for i in range(600):  # 600 sibling stanzas, no exits
            lines += [f"interface eth{i}", "no shutdown"]
        ex("\n".join(lines))
        assert len(calls) > 1
        for call in calls:
            args = call[2::2]
            # bounded preamble: at most configure + ONE interface context
            assert len(args) <= 403, len(args)
            ifaces = [a for a in args if a.startswith("interface ")]
            # every `no shutdown` sits directly under its own interface
            prev = None
            for a in args:
                if a.startswith("interface "):
                    prev = a
                elif a == "no shutdown":
                    assert prev is not None
        # each stanza applied exactly once
        all_ifaces = [a for call in calls for a in call[2::2]
                      if a.startswith("interface ")]
        # replayed context duplicates one interface per boundary at most
        assert len(set(all_ifaces)) == 600
        assert len(all_ifaces) <= 600 + len(calls)
