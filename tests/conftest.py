"""Test environment: hermetic CPU JAX with an 8-device virtual mesh.

Tests must be hermetic (no dependence on the single real TPU chip), and the
multi-chip sharding paths (bng_tpu.parallel) need >1 device. Mirrors the
reference's strategy of running everything against stub platform backends
(SURVEY.md §4.6: _linux.go/_stub.go pairs, nil-safe loader).

The actual guard (force JAX_PLATFORMS=cpu, virtual device count, drop the
axon PJRT factory so nothing can touch the chip) lives in
bng_tpu.utils.jaxenv.force_cpu — the same helper the driver entry points
use. Keep the logic there; this file just invokes it before any backend
initialization.
"""

from bng_tpu.utils.jaxenv import enable_compilation_cache, force_cpu

force_cpu(8)
# Persistent XLA compilation cache: the suite is compile-dominated
# (verdict weakness 5 — ~265s, nearly all compiles), and the tier-1 gate
# runs under a hard timeout. The helper self-guards: on this jaxlib's
# XLA:CPU, cache-DESERIALIZED executables compute wrong results for the
# donated pipeline programs (PERF_NOTES §4), so CPU runs stay uncached
# unless BNG_JAX_CACHE_CPU=1; accelerator runs get the cache. The CPU
# time win comes from the @pytest.mark.slow tier instead.
enable_compilation_cache()

# ---------------------------------------------------------------------------
# BNG_SANITIZE=1 — runtime sanitizer around hot-path tests
# ---------------------------------------------------------------------------
# The dynamic cross-check of bngcheck's static transfer lint
# (bng_tpu/analysis): tests marked `hotpath` run under
# jax.transfer_guard_device_to_host("disallow") + jax.debug_nans, so an
# implicit device->host transfer the lint missed fails the test instead
# of silently blocking the dispatch path. Best-effort on XLA:CPU — the
# d2h guard is inert there (measured, see analysis/sanitize.py); the
# debug_nans half and the planted h2d tests keep teeth everywhere.
# BNG_SANITIZE=strict additionally disallows implicit host->device
# transfers (only hotpath tests whose inputs are explicitly staged
# survive that).

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _bng_sanitize(request):
    from bng_tpu.analysis import sanitize

    if (not sanitize.enabled()
            or request.node.get_closest_marker("hotpath") is None):
        yield
        return
    with sanitize.sanitized(
            h2d="disallow" if sanitize.strict() else "allow"):
        yield
