"""Test environment: hermetic CPU JAX with an 8-device virtual mesh.

Tests must be hermetic (no dependence on the single real TPU chip), and the
multi-chip sharding paths (bng_tpu.parallel) need >1 device. Mirrors the
reference's strategy of running everything against stub platform backends
(SURVEY.md §4.6: _linux.go/_stub.go pairs, nil-safe loader).

The actual guard (force JAX_PLATFORMS=cpu, virtual device count, drop the
axon PJRT factory so nothing can touch the chip) lives in
bng_tpu.utils.jaxenv.force_cpu — the same helper the driver entry points
use. Keep the logic there; this file just invokes it before any backend
initialization.
"""

from bng_tpu.utils.jaxenv import force_cpu

force_cpu(8)
