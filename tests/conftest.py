"""Test environment: hermetic CPU JAX with an 8-device virtual mesh.

Tests must be hermetic (no dependence on the single real TPU chip), and the
multi-chip sharding paths (bng_tpu.parallel) need >1 device. Mirrors the
reference's strategy of running everything against stub platform backends
(SURVEY.md §4.6: _linux.go/_stub.go pairs, nil-safe loader).

The container's sitecustomize registers an `axon` PJRT plugin for the one
real TPU chip in every interpreter; initializing it contends for the chip
and can block test runs while another process holds the claim. Tests force
JAX_PLATFORMS=cpu *and* drop the axon backend factory before any backend
initialization so pytest never touches the chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# sitecustomize has already imported jax with JAX_PLATFORMS=axon, so the env
# var alone is too late — update the live config and drop the axon factory
# so nothing can touch the chip (a stray request fails loudly, never hangs).
import jax

jax.config.update("jax_platforms", "cpu")
# Preload pallas (and its checkify dependency) while the full platform
# registry is intact: its import registers "tpu" lowering rules, which
# fails with "unknown platform" once the factories below are dropped.
try:
    import jax.experimental.pallas  # noqa: F401
    import jax.experimental.pallas.tpu  # noqa: F401
except Exception:  # pragma: no cover - pallas optional on exotic jaxlibs
    pass
try:
    import jax._src.xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover - best effort; jax_platforms=cpu remains
    pass
