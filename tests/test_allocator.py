"""Allocator subsystem tests (pkg/allocator parity)."""

import json

import pytest

from bng_tpu.control.allocator import (
    AllocationRecord,
    DistributedAllocator,
    EpochBitmapAllocator,
    HybridAllocator,
    IPAllocator,
    LocalAllocator,
    MemoryAllocationStore,
)
from bng_tpu.control.allocator.bitmap import BitmapExhaustedError


class TestBitmap:
    def test_allocate_release_cycle(self):
        a = IPAllocator("192.168.1.0/29")  # 8 addrs, net+bcast reserved
        ips = [str(a.allocate(f"s{i}")) for i in range(6)]
        assert len(set(ips)) == 6
        assert "192.168.1.0" not in ips and "192.168.1.7" not in ips
        with pytest.raises(BitmapExhaustedError):
            a.allocate("s7")
        assert a.release("192.168.1.3")
        assert str(a.allocate("s8")) == "192.168.1.3"

    def test_specific_and_owner(self):
        a = IPAllocator("10.1.0.0/24")
        assert a.allocate_specific("10.1.0.50", "alice")
        assert not a.allocate_specific("10.1.0.50", "bob")
        assert a.allocate_specific("10.1.0.50", "alice")  # idempotent for owner
        assert a.owner_of("10.1.0.50") == "alice"

    def test_ipv6_prefix(self):
        a = IPAllocator("2001:db8::/120")
        ip = a.allocate("v6sub")
        assert str(ip).startswith("2001:db8::")
        assert a.release(ip)

    def test_json_roundtrip(self):
        a = IPAllocator("10.2.0.0/24")
        a.allocate("x")
        a.allocate("y")
        b = IPAllocator.from_json(a.to_json())
        assert b.allocated_count == a.allocated_count
        assert b.owners == a.owners

    def test_out_of_range_rejected(self):
        a = IPAllocator("10.3.0.0/24")
        with pytest.raises(ValueError):
            a.offset_of("10.4.0.1")


class TestEpochBitmap:
    def test_epoch_expiry_o1(self):
        a = EpochBitmapAllocator("10.5.0.0/28")
        ip1 = a.allocate("s1")
        assert a.owner_of(ip1) == "s1"
        a.advance_epoch()  # s1 now one epoch old - still live
        assert a.owner_of(ip1) == "s1"
        a.advance_epoch()  # two epochs -> expired, lazily
        assert a.owner_of(ip1) is None

    def test_touch_keeps_alive(self):
        a = EpochBitmapAllocator("10.5.1.0/28")
        ip = a.allocate("s1")
        for _ in range(5):
            a.advance_epoch()
            assert a.touch(ip), "renewed lease must stay live"
        assert a.owner_of(ip) == "s1"

    def test_expired_slots_reclaimed(self):
        a = EpochBitmapAllocator("10.5.2.0/30")  # 4 slots
        for i in range(4):
            a.allocate(f"s{i}")
        with pytest.raises(RuntimeError):
            a.allocate("overflow")
        a.advance_epoch()
        a.advance_epoch()  # all expired
        ip = a.allocate("fresh")  # lazy reclaim works
        assert a.owner_of(ip) == "fresh"
        assert a.live_count() == 1

    def test_snapshot_roundtrip(self):
        a = EpochBitmapAllocator("10.5.3.0/28")
        ip = a.allocate("s1")
        a.advance_epoch()
        b = EpochBitmapAllocator.from_json(a.to_json())
        assert b.owner_of(ip) == "s1"
        assert b.epoch == a.epoch


class TestDistributed:
    def test_same_subscriber_same_ip_across_nodes(self):
        """Hashring determinism: no coordination needed for agreement."""
        store = MemoryAllocationStore()
        n1 = DistributedAllocator("10.6.0.0/24", store, node_id="n1")
        n2 = DistributedAllocator("10.6.0.0/24", store, node_id="n2")
        ip1 = n1.allocate("sub-42")
        ip2 = n2.allocate("sub-42")
        assert ip1 == ip2

    def test_conflict_probes_forward(self):
        store = MemoryAllocationStore()
        a = DistributedAllocator("10.6.1.0/24", store)
        ip1 = a.allocate("sub-A")
        # sub-B hashing to the same first candidate must probe onward
        taken = {ip1}
        for i in range(50):
            ip = a.allocate(f"sub-B{i}")
            assert ip not in taken
            taken.add(ip)

    def test_expiry_reclaims(self):
        t = [1000.0]
        store = MemoryAllocationStore()
        a = DistributedAllocator("10.6.2.0/29", store, lease_seconds=60,
                                 clock=lambda: t[0])
        ips = [a.allocate(f"s{i}") for i in range(6)]
        assert all(ips)
        assert a.allocate("s-late") is None  # full
        t[0] += 3600  # all leases expired
        assert a.allocate("s-late") is not None

    def test_sync_from_store(self):
        store = MemoryAllocationStore()
        a = DistributedAllocator("10.6.3.0/24", store)
        a.allocate("s1")
        b = DistributedAllocator("10.6.3.0/24", store, node_id="n2")
        assert b.sync_from_store() == 1


class FlakyPrimary:
    """Test double: a primary allocator with a controllable health switch
    (the reference's controllable health-checker pattern, SURVEY.md §4.6)."""

    def __init__(self):
        self.healthy = True
        self.inner = LocalAllocator("10.7.0.0/24")

    def allocate(self, sid):
        if not self.healthy:
            raise ConnectionError("nexus unreachable")
        return self.inner.allocate(sid)

    def release(self, sid):
        if not self.healthy:
            raise ConnectionError("nexus unreachable")
        return self.inner.release(sid)


class TestHybrid:
    def test_partition_fallback_and_reconcile(self):
        primary = FlakyPrimary()
        h = HybridAllocator(primary, "100.64.0.0/24", failure_threshold=2)
        ip = h.allocate("s1")
        assert ip.startswith("10.7.0.")
        assert not h.is_partition_active()

        primary.healthy = False
        assert h.allocate("s2") is None  # failure 1
        ip3 = h.allocate("s3")  # failure 2 -> partition -> fallback
        assert h.is_partition_active()
        assert ip3.startswith("100.64.0.")
        assert len(h.fallback_allocations) == 1

        primary.healthy = True
        migrated, renumbered = h.reconcile()
        assert migrated == 1
        # disjoint fallback range -> the subscriber gets a primary address
        assert len(renumbered) == 1
        fb, new_ip = renumbered[0]
        assert fb.subscriber_id == "s3" and new_ip.startswith("10.7.0.")
        assert not h.is_partition_active()
