"""TLS/mTLS on the cluster wire + ETSI TLS delivery (VERDICT r4
missing #4/#5). Live handshakes against real openssl-generated
certificates, in the style of tests/test_ztp_tls.py.

Parity: pkg/ha/sync.go:151-185 (TLS + mutual TLS on HA session
replication), pkg/intercept/exporter.go:191-317 (TLS delivery of
HI2/HI3 handover PDUs).
"""

import os
import socket
import ssl
import struct
import subprocess
import threading

import pytest

from bng_tpu.control import ztp_tls as zt
from bng_tpu.control.cluster_http import (
    ClusterServer,
    HTTPActiveProxy,
    HTTPStorePeer,
)
from bng_tpu.control.crdt import MODE_WRITE, DistributedStore
from bng_tpu.control.ha import (
    ActiveSyncer,
    InMemorySessionStore,
    SessionState,
    StandbySyncer,
)
from bng_tpu.control.intercept import (
    ETSIExporter,
    InterceptRecord,
    TLSDeliverySink,
    parse_etsi_pdu,
)
from bng_tpu.control.ztp_tls import ServerTLSConfig, TLSConfig

from tests.test_cluster_http import wait_until


def _selfsigned(tmp, cn):
    key = os.path.join(tmp, f"{cn}.key")
    crt = os.path.join(tmp, f"{cn}.crt")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", "365",
         "-subj", f"/CN={cn}",
         "-addext", f"subjectAltName=DNS:{cn},IP:127.0.0.1"],
        check=True, capture_output=True)
    with open(crt) as f:
        pem = f.read()
    der = zt.pem_to_der(pem)[0]
    return {"key": key, "crt": crt, "pem": pem, "der": der,
            "pin": zt.cert_fingerprint(der)}


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("cluster_tls"))
    return {
        "server": _selfsigned(tmp, "active.cluster.test"),
        "client": _selfsigned(tmp, "standby.cluster.test"),
        "lea": _selfsigned(tmp, "lea.collector.test"),
    }


def _client_cfg(certs, pin=None, mtls=False):
    cfg = TLSConfig(
        pinned_certs=[pin or certs["server"]["pin"]],
        require_valid_chain=False)
    if mtls:
        cfg.client_cert_file = certs["client"]["crt"]
        cfg.client_key_file = certs["client"]["key"]
    return cfg


class TestClusterTLS:
    def test_ha_full_sync_and_sse_over_pinned_tls(self, certs, request):
        active = ActiveSyncer(InMemorySessionStore())
        srv = ClusterServer(tls=ServerTLSConfig(
            cert_file=certs["server"]["crt"],
            key_file=certs["server"]["key"])).mount_ha(active).start()
        request.addfinalizer(srv.close)
        assert srv.url.startswith("https://")

        active.push_change(SessionState("s1", mac="02:00:00:00:00:01",
                                        ip=0x0A000001))
        store = InMemorySessionStore()
        standby = StandbySyncer(store, transport=lambda: HTTPActiveProxy(
            srv.url, on_stream_end=lambda: standby.disconnect(),
            tls=_client_cfg(certs)))
        standby.tick(now=0.0)
        assert standby.connected
        assert len(store) == 1  # full sync over TLS

        # live SSE delta rides the same verified channel
        active.push_change(SessionState("s2", ip=0x0A000002))
        assert wait_until(lambda: store.get("s2") is not None)

    def test_wrong_pin_refused_before_any_request(self, certs, request):
        active = ActiveSyncer(InMemorySessionStore())
        srv = ClusterServer(tls=ServerTLSConfig(
            cert_file=certs["server"]["crt"],
            key_file=certs["server"]["key"])).mount_ha(active).start()
        request.addfinalizer(srv.close)
        with pytest.raises(zt.CertificateValidationError):
            HTTPActiveProxy(srv.url,
                            tls=_client_cfg(certs, pin="ab" * 32))

    def test_plaintext_client_cannot_reach_tls_listener(self, certs, request):
        srv = ClusterServer(tls=ServerTLSConfig(
            cert_file=certs["server"]["crt"],
            key_file=certs["server"]["key"])) \
            .mount_ha(ActiveSyncer(InMemorySessionStore())).start()
        request.addfinalizer(srv.close)
        with pytest.raises(ConnectionError):
            HTTPActiveProxy(f"http://{srv.host}:{srv.port}")

    def test_mtls_requires_client_identity(self, certs, request):
        """client_ca set -> the listener demands a verified client cert
        (sync.go's mutual-TLS mode)."""
        active = ActiveSyncer(InMemorySessionStore())
        srv = ClusterServer(tls=ServerTLSConfig(
            cert_file=certs["server"]["crt"],
            key_file=certs["server"]["key"],
            client_ca_file=certs["client"]["crt"])).mount_ha(active).start()
        request.addfinalizer(srv.close)

        # no client identity: handshake (or first request) must fail
        with pytest.raises((ConnectionError, ssl.SSLError,
                            zt.CertificateValidationError)):
            HTTPActiveProxy(srv.url, tls=_client_cfg(certs))

        # with the identity the sync works end to end
        proxy = HTTPActiveProxy(srv.url, tls=_client_cfg(certs, mtls=True))
        active.push_change(SessionState("m1", ip=1))
        sessions, seq = proxy.full_sync()
        assert [s.session_id for s in sessions] == ["m1"]

    def test_crdt_anti_entropy_over_tls(self, certs, request):
        a = DistributedStore("a", mode=MODE_WRITE)
        b = DistributedStore("b", mode=MODE_WRITE)
        srv_b = ClusterServer(tls=ServerTLSConfig(
            cert_file=certs["server"]["crt"],
            key_file=certs["server"]["key"])).mount_store(b).start()
        request.addfinalizer(srv_b.close)
        a.add_peer(HTTPStorePeer(srv_b.url, tls=_client_cfg(certs)))

        a.put("sub/1", b"ip=10.0.0.1")
        b.put("sub/2", b"\x00\x01\xff")
        a.tick()
        assert a.get("sub/2") == b"\x00\x01\xff"
        assert b.get("sub/1") == b"ip=10.0.0.1"


class _LEACollector:
    """Minimal TLS collector: accepts connections, reads 4B-length-framed
    PDUs, records them."""

    def __init__(self, certs):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certs["lea"]["crt"], certs["lea"]["key"])
        self._ctx = ctx
        self._raw = socket.create_server(("127.0.0.1", 0))
        self.port = self._raw.getsockname()[1]
        self.pdus: list[bytes] = []
        self.accepting = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while True:
            try:
                raw, _ = self._raw.accept()
            except OSError:
                return
            if not self.accepting:
                raw.close()
                continue
            try:
                conn = self._ctx.wrap_socket(raw, server_side=True)
                conn.settimeout(5.0)
                while True:
                    hdr = self._read_n(conn, 4)
                    if hdr is None:
                        break
                    n = struct.unpack(">I", hdr)[0]
                    body = self._read_n(conn, n)
                    if body is None:
                        break
                    self.pdus.append(body)
            except (ssl.SSLError, OSError):
                continue

    @staticmethod
    def _read_n(conn, n):
        buf = b""
        while len(buf) < n:
            try:
                got = conn.recv(n - len(buf))
            except (TimeoutError, OSError):
                return None
            if not got:
                return None
            buf += got
        return buf

    def close(self):
        self._raw.close()


class TestETSITLSDelivery:
    def _record(self):
        return InterceptRecord(
            id="r1", liid="LIID-7", warrant_id="w1", timestamp=1000.0,
            record_type="IRI", event_type="session-start",
            session_id="sess-1", subscriber_id="sub-9",
            source_ip="10.0.0.5", dest_ip="8.8.8.8",
            source_port=40000, dest_port=53, protocol=17,
            direction="up", payload=b"pkt")

    def test_hi2_pdu_delivered_over_pinned_tls(self, certs, request):
        lea = _LEACollector(certs)
        request.addfinalizer(lea.close)
        sink = TLSDeliverySink("127.0.0.1", lea.port, TLSConfig(
            pinned_certs=[certs["lea"]["pin"]], require_valid_chain=False))
        request.addfinalizer(sink.close)
        exporter = ETSIExporter(sink, country_code="GB")

        exporter.deliver_iri(self._record())
        assert wait_until(lambda: len(lea.pdus) == 1)
        parsed = parse_etsi_pdu(lea.pdus[0])
        assert parsed["liid"] == "LIID-7"
        assert parsed["handover"] == ETSIExporter.HI2
        assert sink.stats["delivered"] == 1

    def test_wrong_pin_delivers_nothing(self, certs, request):
        lea = _LEACollector(certs)
        request.addfinalizer(lea.close)
        sink = TLSDeliverySink("127.0.0.1", lea.port, TLSConfig(
            pinned_certs=["cd" * 32], require_valid_chain=False))
        request.addfinalizer(sink.close)
        ETSIExporter(sink).deliver_iri(self._record())
        assert sink.stats["connect_failures"] == 1
        assert sink.stats["delivered"] == 0
        assert lea.pdus == []  # zero HI bytes left the box

    def test_outage_buffers_then_flushes(self, certs, request):
        t = [1000.0]
        lea = _LEACollector(certs)
        request.addfinalizer(lea.close)
        lea.accepting = False  # collector down
        sink = TLSDeliverySink(
            "127.0.0.1", lea.port,
            TLSConfig(pinned_certs=[certs["lea"]["pin"]],
                      require_valid_chain=False),
            clock=lambda: t[0], auto_flush=False)  # test drives flush()
        request.addfinalizer(sink.close)
        exporter = ETSIExporter(sink)
        exporter.deliver_iri(self._record())
        exporter.deliver_cc(self._record())
        assert sink.stats["delivered"] == 0 and len(sink._buffer) == 2

        lea.accepting = True  # collector back
        t[0] += 10.0
        assert sink.flush()
        assert wait_until(lambda: len(lea.pdus) == 2)
        assert sink.stats["delivered"] == 2
        assert parse_etsi_pdu(lea.pdus[1])["handover"] == ETSIExporter.HI3

    def test_auto_flush_self_heals_after_outage(self, certs, request):
        """Review r5: nothing external needs to drive flush() — the
        sink's own backoff thread redials and drains once the collector
        returns, so one transient outage cannot halt delivery forever."""
        lea = _LEACollector(certs)
        request.addfinalizer(lea.close)
        lea.accepting = False
        sink = TLSDeliverySink(
            "127.0.0.1", lea.port,
            TLSConfig(pinned_certs=[certs["lea"]["pin"]],
                      require_valid_chain=False),
            reconnect_backoff_s=0.2)
        request.addfinalizer(sink.close)
        ETSIExporter(sink).deliver_iri(self._record())
        assert sink.stats["delivered"] == 0
        lea.accepting = True  # collector recovers; NOBODY calls flush()
        assert wait_until(lambda: sink.stats["delivered"] == 1, timeout=5.0)
        # the sink counts `delivered` at socket write; the collector
        # THREAD appends to pdus after its read — wait for that side
        # too instead of racing it on a loaded host
        assert wait_until(lambda: len(lea.pdus) == 1, timeout=5.0)
