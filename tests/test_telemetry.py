"""Telemetry subsystem tests (bng_tpu/telemetry): disarmed-overhead
bound, histogram merge laws, flight-recorder wrap + anomaly triggers
(incl. forced backend fallback), Chrome-trace export schema, and DORA
through tracing — host-only through the fleet in the fast tier, full
engine + scheduler + fleet under @pytest.mark.slow.

`make verify-telemetry` runs the 'telemetry and not slow' set with
BNG_TELEMETRY=1 in the environment (< 30 s — no XLA compiles there).
"""

from __future__ import annotations

import json
import os
import sys
import timeit

import numpy as np
import pytest

from bng_tpu.chaos.faults import FaultPlan, FaultSpec, SimClock, armed
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import _mac, build_fleet, dora_with_retries
from bng_tpu.telemetry import (FlightRecorder, LatencyHist, RecorderConfig,
                               Tracer, chrome_trace)
from bng_tpu.telemetry import spans

pytestmark = pytest.mark.telemetry

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    spans.disarm()


# ---------------------------------------------------------------------------
# disarmed overhead: the production state must stay near-free
# ---------------------------------------------------------------------------

class TestDisarmedOverhead:
    def test_hooks_disarmed_ns_per_call_bounded(self):
        """Each disarmed hook is one module-global load + is-None
        compare. Measured 77-84 ns/call on the dev container (PERF_NOTES
        §8); the bound here is deliberately loose for noisy CI — what it
        pins is the ORDER (ns, not us): an accidental dict lookup or
        allocation on the disarmed path would blow through it."""
        assert not spans.enabled()
        n = 200_000
        for fn, args in ((spans.t, ()), (spans.stamp, (spans.DISPATCH,)),
                         (spans.lap, (spans.DISPATCH, None))):
            ns = (timeit.Timer(lambda: fn(*args)).timeit(n) / n) * 1e9
            assert ns < 2_000, f"{fn.__name__}: {ns:.0f} ns/call"

    def test_disarmed_hooks_are_noops(self):
        assert spans.t() is None
        assert spans.begin_batch(spans.LANE_ENGINE, 8) is None
        spans.lap(spans.DISPATCH, None)
        spans.end_batch(None)
        spans.add(shed=5)
        assert spans.trigger("worker_death") is None
        with spans.span(spans.SLOW):
            pass  # the no-op singleton


# ---------------------------------------------------------------------------
# histograms: accuracy, merge laws, wire round-trip
# ---------------------------------------------------------------------------

class TestLatencyHist:
    def test_percentiles_track_numpy_within_bucket_error(self):
        rng = np.random.default_rng(7)
        vals = rng.lognormal(3.0, 1.5, 50_000)  # us, heavy tail
        h = LatencyHist()
        h.record_many(vals)
        for q in (50, 90, 99, 99.9):
            exact = float(np.percentile(vals, q))
            got = h.percentile(q)
            assert abs(got - exact) / exact < 0.126, (q, got, exact)

    def test_scalar_and_vector_record_agree(self):
        rng = np.random.default_rng(8)
        vals = rng.lognormal(2.0, 2.0, 2_000)
        a, b = LatencyHist(), LatencyHist()
        for v in vals:
            a.record(float(v))
        b.record_many(vals)
        assert (a.counts == b.counts).all()
        assert a.n == b.n

    def test_merge_is_associative_and_commutative(self):
        """The property that makes per-worker/per-shard histograms
        mergeable in ANY gather order: counts are plain addition."""
        rng = np.random.default_rng(9)
        parts = [rng.lognormal(3, 1, 5_000) for _ in range(3)]
        a, b, c = (LatencyHist() for _ in range(3))
        for h, p in zip((a, b, c), parts):
            h.record_many(p)
        ab_c = a.copy().merge(b.copy()).merge(c.copy())
        a_bc = a.copy().merge(b.copy().merge(c.copy()))
        cba = c.copy().merge(b.copy()).merge(a.copy())
        for m in (a_bc, cba):
            assert (ab_c.counts == m.counts).all()
            assert ab_c.n == m.n
            assert ab_c.sum_us == pytest.approx(m.sum_us)
        whole = LatencyHist()
        whole.record_many(np.concatenate(parts))
        assert (whole.counts == ab_c.counts).all()

    def test_wire_roundtrip(self):
        h = LatencyHist()
        h.record_many(np.random.default_rng(1).lognormal(4, 1, 1_000))
        rt = LatencyHist.from_dict(json.loads(json.dumps(h.to_dict())))
        assert (rt.counts == h.counts).all()
        assert rt.n == h.n and rt.max_us == h.max_us
        assert rt.percentile(99) == h.percentile(99)

    def test_empty_hist(self):
        h = LatencyHist()
        assert h.percentile(99) == 0.0
        assert h.summary()["count"] == 0


# ---------------------------------------------------------------------------
# flight recorder: wrap + anomaly triggers
# ---------------------------------------------------------------------------

def _traced_batches(tracer, n, total_sleep_us=0.0, shed=0):
    for _ in range(n):
        tok = tracer.begin(spans.LANE_ENGINE, 16)
        t0 = tracer.clock()
        tracer.lap(spans.DISPATCH, t0, tok)
        if shed:
            tracer.add(tok, shed=shed)
        tracer.end(tok)


class TestFlightRecorder:
    def test_ring_wraps_keeping_last_n(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(capacity=16,
                                            out_dir=str(tmp_path)))
        tr = Tracer(recorder=rec)
        _traced_batches(tr, 50)
        meta = rec.snapshot_meta()
        assert meta["valid_records"] == 16
        records = rec.records()
        assert len(records) == 16
        # oldest-first, exactly the LAST 16 of the 50
        assert [r["seq"] for r in records] == list(range(34, 50))
        assert all(r["stages_us"].get("total", 0) >= 0 for r in records)

    def test_latency_excursion_trigger_dumps(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(
            capacity=8, latency_budget_us=0.000001,
            out_dir=str(tmp_path)))
        tr = Tracer(recorder=rec)
        _traced_batches(tr, 1)
        assert rec.triggers.get("latency_excursion") == 1
        assert len(rec.dump_paths) == 1
        d = json.load(open(rec.dump_paths[0]))
        assert d["reason"] == "latency_excursion"
        assert d["meta"]["backend"] == "unknown"

    def test_shed_burst_trigger_dumps(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(capacity=8, shed_burst=4,
                                            out_dir=str(tmp_path)))
        tr = Tracer(recorder=rec)
        _traced_batches(tr, 1, shed=10)
        assert rec.triggers.get("shed_burst") == 1
        # and the token-less path (fleet outside a traced batch)
        rec.note_shed(10)
        assert rec.triggers["shed_burst"] == 2

    def test_worker_death_trigger_via_module_hook(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(capacity=8,
                                            out_dir=str(tmp_path)))
        with spans.armed(Tracer(recorder=rec)):
            path = spans.trigger("worker_death", "worker 2 lost a batch")
        assert path is not None
        d = json.load(open(path))
        assert d["reason"] == "worker_death"
        assert d["detail"] == "worker 2 lost a batch"

    def test_dump_rate_limit_and_cap(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(
            capacity=4, min_dump_interval_s=3600.0,
            out_dir=str(tmp_path)))
        with spans.armed(Tracer(recorder=rec)):
            assert spans.trigger("worker_death") is not None
            assert spans.trigger("worker_death") is None  # rate-limited
        assert rec.triggers["worker_death"] == 2  # counted regardless

    def test_backend_fallback_dump_and_json_flag(self, tmp_path):
        """The acceptance path: a CPU-fallback bench run must dump the
        flight recorder and flag it at the TOP of the JSON. Drives
        bench._finalize_diag / _order_line directly (the code the child
        dispatch runs before every print)."""
        sys.path.insert(0, _ROOT)
        try:
            import bench
        finally:
            sys.path.remove(_ROOT)
        rec = FlightRecorder(RecorderConfig(capacity=8,
                                            out_dir=str(tmp_path)))
        rec.set_backend("cpu")
        old = dict(bench._DIAG)
        bench._DIAG.clear()
        try:
            with spans.armed(Tracer(recorder=rec)) as tr:
                _traced_batches(tr, 3)
                bench._DIAG["backend_fallback"] = "cpu"
                bench._DIAG["backend_error"] = "probe timed out"
                bench._finalize_diag()
                line = bench._order_line({"metric": "m", "value": 1.0,
                                          **bench._DIAG})
            assert bench._DIAG["flight_record"].startswith(str(tmp_path))
            d = json.load(open(bench._DIAG["flight_record"]))
            assert d["reason"] == "backend_fallback"
            assert d["meta"]["backend"] == "cpu"
            assert len(d["records"]) == 3
            # fallback keys lead the object
            assert list(line)[:3] == ["backend_fallback", "backend_error",
                                      "flight_record"]
        finally:
            bench._DIAG.clear()
            bench._DIAG.update(old)

    def test_invariant_violation_triggers_dump(self, tmp_path):
        """A planted double-lease must land a flight dump the moment the
        auditor proves it (the chaos <-> telemetry wiring)."""
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(2, clock)
        macs = [_mac(i) for i in range(8)]
        leased = dora_with_retries(fleet, macs, clock)
        victim_ip = next(iter(leased.values()))
        fleet._inline[0].restore_state({"session_seq": 0, "leases": [{
            "mac": _mac(999).hex(), "ip": victim_ip, "pool_id": 1,
            "expiry": 2_000_000_000, "circuit_id": "", "remote_id": "",
            "s_tag": 0, "c_tag": 0, "session_id": "forged",
            "client_class": 0, "username": "", "qos_policy": ""}]})
        rec = FlightRecorder(RecorderConfig(capacity=8,
                                            out_dir=str(tmp_path)))
        with spans.armed(Tracer(recorder=rec)):
            report = audit_invariants(pools=pools, fleet=fleet,
                                      fastpath=fastpath)
        assert not report.ok
        assert rec.triggers.get("invariant_violation") == 1
        d = json.load(open(rec.dump_paths[0]))
        assert "double-lease" in d["detail"]
        fleet.close()


# ---------------------------------------------------------------------------
# Chrome-trace export schema
# ---------------------------------------------------------------------------

class TestChromeTrace:
    def test_export_schema(self):
        tr = Tracer(keep_events=100)
        with spans.armed(tr):
            for _ in range(4):
                tok = spans.begin_batch(spans.LANE_EXPRESS_L, 8)
                t0 = spans.t()
                spans.lap(spans.DISPATCH, t0, tok)
                spans.end_batch(tok)
        trace = json.loads(json.dumps(chrome_trace(tr)))
        assert set(trace) >= {"traceEvents", "displayTimeUnit"}
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        ms = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert xs and ms
        for e in xs:
            assert {"name", "cat", "pid", "tid", "ts", "dur"} <= set(e)
            assert e["dur"] > 0 and e["ts"] >= 0
            assert e["name"] in spans.STAGE_NAMES
        assert {"total", "dispatch"} <= {e["name"] for e in xs}
        # lane thread metadata names the express lane
        assert any(e["name"] == "thread_name"
                   and "express" in e["args"]["name"] for e in ms)

    def test_export_without_events_refuses(self):
        with pytest.raises(ValueError):
            chrome_trace(Tracer())


# ---------------------------------------------------------------------------
# DORA through tracing — host-only fleet tier (no XLA compile)
# ---------------------------------------------------------------------------

class TestFleetTracing:
    def test_dora_through_fleet_records_stages(self, tmp_path):
        """Full DORA through 2 inline workers with the tracer armed:
        admit/fleet stages populate from the parent, and the workers'
        per-frame histograms merge into the `worker` stage — the
        cross-worker histogram merge, live."""
        rec = FlightRecorder(RecorderConfig(capacity=32,
                                            out_dir=str(tmp_path)))
        with spans.armed(Tracer(recorder=rec)) as tr:
            clock = SimClock()
            fleet, pools, fastpath = build_fleet(2, clock)
            macs = [_mac(i) for i in range(16)]
            leased = dora_with_retries(fleet, macs, clock)
            assert len(leased) == len(macs)
            bd = tr.breakdown()
        assert {"admit", "fleet", "worker"} <= set(bd)
        assert bd["worker"]["count"] >= 2 * len(macs)  # DISCOVER+REQUEST
        assert bd["worker"]["p99_us"] > 0
        fleet.close()

    def test_worker_hists_merge_across_both_workers(self):
        """Both shards' workers must contribute to the merged worker
        stage — the per-worker deltas fold through _absorb."""
        with spans.armed(Tracer()) as tr:
            clock = SimClock()
            fleet, _pools, _fastpath = build_fleet(2, clock)
            macs = [_mac(i) for i in range(32)]
            dora_with_retries(fleet, macs, clock)
            from bng_tpu.control.fleet import shard_for_mac
            shards = {shard_for_mac(m, 2) for m in macs}
            assert shards == {0, 1}  # both workers saw traffic
            assert tr.hists[spans.WORKER].n >= 2 * len(macs)
        fleet.close()

    def test_chaos_worker_kill_dumps_flight_record(self, tmp_path):
        """A chaos-killed worker (fleet.scatter kill) must both count a
        worker failure AND leave a flight dump."""
        rec = FlightRecorder(RecorderConfig(capacity=16,
                                            out_dir=str(tmp_path)))
        with spans.armed(Tracer(recorder=rec)):
            clock = SimClock()
            fleet, pools, fastpath = build_fleet(2, clock)
            plan = FaultPlan(1, [FaultSpec("fleet.scatter", "kill",
                                           at_hit=1)])
            with armed(plan, log=False):
                dora_with_retries(fleet, [_mac(i) for i in range(8)],
                                  clock)
        assert fleet.worker_failures >= 1
        assert rec.triggers.get("worker_death", 0) >= 1
        assert rec.dump_paths
        fleet.close()


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

class TestDispatchFailureSlotSafety:
    def test_chaos_dispatch_failure_releases_record_slot(self):
        """A chaos-injected dispatch failure (engine.dispatch `fail`,
        raised BEFORE the jit call) must cancel the open batch record —
        a leaked slot per failure would exhaust the pool exactly during
        the failure storms the flight recorder exists to capture."""
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine, FaultInjectedError
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        fp = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=4)
        fp.set_server_config(b"\x02" * 6, ip_to_u32("10.0.0.1"))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        engine = Engine(fp, nat, batch_size=8)
        tr = Tracer()
        with spans.armed(tr):
            n_fails = tr.OPEN_SLOTS + 4  # more failures than slots
            plan = FaultPlan(1, [FaultSpec("engine.dispatch", "fail",
                                           at_hit=1, count=n_fails)])
            with armed(plan, log=False):
                for _ in range(n_fails):
                    with pytest.raises(FaultInjectedError):
                        engine.process([b"\x00" * 64])
            assert len(tr._free) == tr.OPEN_SLOTS
            assert tr.records_dropped == 0


class TestMetricsExport:
    def test_stage_latency_family_and_counters(self, tmp_path):
        from bng_tpu.control.metrics import BNGMetrics

        rec = FlightRecorder(RecorderConfig(capacity=8,
                                            out_dir=str(tmp_path)))
        tr = Tracer(recorder=rec)
        _traced_batches(tr, 5)
        with spans.armed(tr):
            spans.trigger("worker_death", "x")
        m = BNGMetrics()
        m.attach_telemetry(tr)
        m.attach_telemetry(tr)  # idempotent
        m.collect_telemetry(tr)
        text = m.expose()
        assert 'bng_stage_latency_us_bucket{stage="total",le="+Inf"} 5' \
            in text
        assert 'bng_stage_latency_us_count{stage="dispatch"} 5' in text
        assert 'bng_flight_dumps_total{reason="worker_death"} 1' in text
        assert "bng_telemetry_batch_records_total 5" in text


# ---------------------------------------------------------------------------
# profiling percentile (satellite fix)
# ---------------------------------------------------------------------------

class TestStepDurationsPercentile:
    def test_matches_numpy_percentile_property(self):
        """Property test pinning the sort-once interpolating percentile
        to numpy.percentile's default (linear) method."""
        from bng_tpu.utils.profiling import StepDurations

        rng = np.random.default_rng(11)
        for size in (1, 2, 3, 7, 50, 501):
            vals = rng.lognormal(2, 1.3, size).tolist()
            sd = StepDurations(us=vals, source="device")
            for q in (0.0, 10.0, 25.0, 50.0, 90.0, 99.0, 99.9, 100.0):
                assert sd.percentile(q) == pytest.approx(
                    float(np.percentile(np.asarray(vals), q)),
                    rel=1e-12, abs=1e-12), (size, q)

    def test_sort_cache_and_empty(self):
        from bng_tpu.utils.profiling import StepDurations

        sd = StepDurations(us=[], source="none")
        assert sd.percentile(99) == 0.0
        sd2 = StepDurations(us=[3.0, 1.0, 2.0], source="device")
        assert sd2.percentile(50) == 2.0
        assert sd2.percentile(50) == 2.0  # cached-sort path
        with pytest.raises(ValueError):
            sd2.percentile(101.0)


# ---------------------------------------------------------------------------
# full engine + scheduler + fleet e2e (XLA compiles: slow tier)
# ---------------------------------------------------------------------------

def _build_engine_stack(workers: int = 2, scheduler: bool = True):
    from bng_tpu.control.admission import AdmissionConfig
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
    from bng_tpu.control.nat import NATManager
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32, parse_mac

    smac = parse_mac("02:aa:bb:cc:dd:01")
    sip = ip_to_u32("10.0.0.1")
    fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=64,
                        cid_nbuckets=64, max_pools=4, update_slots=256)
    fp.set_server_config(smac, sip)
    pools = PoolManager(fp)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=16, gateway=sip,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(smac, sip, pools, fastpath_tables=fp)
    engine = Engine(fp, nat, batch_size=64, slow_path=server.handle_frame)
    fleet = SlowPathFleet(
        FleetSpec.from_pool_manager(smac, sip, pools),
        n_workers=workers, pools=pools, mode="inline",
        # compile-cold first batches must not be deadline-shed
        admission=AdmissionConfig(inbox_capacity=512, deadline_ms=60_000.0),
        table_sink=fp)
    engine.slow_path_batch = fleet.handle_batch
    target = engine
    if scheduler:
        target = TieredScheduler(engine, SchedulerConfig(
            express_batch=16, bulk_batch=64))
    return target, fleet


def _dora_frames():
    from bng_tpu.control import dhcp_codec, packets

    def discover(mac, xid):
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def request(mac, offer_frame, xid):
        od = packets.decode(offer_frame)
        off = dhcp_codec.decode(od.payload)
        p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid,
                                     requested_ip=off.yiaddr,
                                     server_id=od.src_ip)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    return discover, request


@pytest.mark.slow
class TestDoraTracingE2E:
    def test_dora_through_scheduler_and_fleet(self, tmp_path):
        """The tentpole e2e: DORA for 32 subscribers through the tiered
        scheduler (express lane), the slow-path fleet (2 inline workers)
        and back — with the tracer armed the whole way. Every lifecycle
        stage the scheduler path exercises must land samples, the flight
        recorder must hold per-batch records, and the span log must
        export a valid Chrome trace."""
        rec = FlightRecorder(RecorderConfig(capacity=64,
                                            out_dir=str(tmp_path)))
        tr = Tracer(recorder=rec, keep_events=1 << 12)
        sched, fleet = _build_engine_stack(workers=2, scheduler=True)
        discover, request = _dora_frames()
        macs = [(0x02D0 << 32 | i).to_bytes(6, "big") for i in range(32)]
        with spans.armed(tr):
            res = sched.process([discover(m, 0x100 + i)
                                 for i, m in enumerate(macs)])
            offers = {i: f for i, f in res["slow"] if f is not None}
            assert len(offers) == len(macs)
            res2 = sched.process([request(m, offers[i], 0x200 + i)
                                  for i, m in enumerate(macs)])
            assert sum(1 for _i, f in res2["slow"] if f is not None) \
                == len(macs)
            # renewal DISCOVERs answered on device (express lane TX)
            res3 = sched.process([discover(m, 0x300 + i)
                                  for i, m in enumerate(macs)])
            assert len(res3["tx"]) == len(macs)
            bd = tr.breakdown()
        for stage in ("lane_wait", "dispatch", "device_wait", "fleet",
                      "worker", "slow_path", "reply", "total"):
            assert stage in bd, (stage, sorted(bd))
            assert bd[stage]["count"] > 0
        assert tr.seq >= 3  # at least one record per exchange batch
        assert rec.snapshot_meta()["valid_records"] == min(tr.seq, 64)
        trace = chrome_trace(tr)
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert len(xs) >= tr.seq  # every batch contributes spans
        assert {"dispatch", "device_wait", "total"} <= {e["name"]
                                                        for e in xs}
        fleet.close()

    def test_engine_pipelined_ring_tracing(self):
        """The ring stage: the pipelined engine loop over a PyRing must
        attribute ring assemble time and keep records balanced (every
        begun batch ends — the open-slot pool never leaks)."""
        from bng_tpu.runtime.ring import PyRing

        engine, fleet = _build_engine_stack(workers=1, scheduler=False)
        discover, _request = _dora_frames()
        ring = PyRing(nframes=256, frame_size=2048)
        with spans.armed(Tracer()) as tr:
            for i in range(32):
                ring.rx_push(discover(
                    (0x02D1 << 32 | i).to_bytes(6, "big"), 0x400 + i),
                    from_access=True)
            engine.process_ring_pipelined(ring)
            engine.process_ring_pipelined(ring)
            engine.flush_pipeline()
            bd = tr.breakdown()
            assert "ring" in bd and bd["ring"]["count"] >= 1
            assert "reply" in bd
            # the open-slot pool drained back: all begun records ended
            assert len(tr._free) == tr.OPEN_SLOTS
        fleet.close()

    def test_loadtest_harness_reports_histogram_percentiles(self):
        from bng_tpu.loadtest import BenchmarkConfig, DHCPBenchmark

        engine, fleet = _build_engine_stack(workers=1, scheduler=False)
        cfg = BenchmarkConfig(batch_size=32, duration_s=0.5, warmup_s=0.5,
                              unique_macs=64)
        res = DHCPBenchmark(engine, cfg).run()
        assert res.requests > 0
        assert res.request_p50_us > 0
        assert res.request_p999_us >= res.request_p99_us \
            >= res.request_p50_us
        assert res.latency_p999_us >= res.latency_p99_us
        d = res.to_dict()
        assert "request_p999_us" in d and "latency_p999_us" in d
        fleet.close()

    def test_process_fleet_restores_telemetry_env(self):
        """Spawning a process fleet under an armed tracer must not leak
        BNG_TELEMETRY=1 into the parent environment — a leaked flag
        would force-arm every later BNGApp in this process and make
        every later fleet's workers pay armed per-frame costs."""
        from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.utils.net import ip_to_u32

        before = os.environ.get("BNG_TELEMETRY")
        sip = ip_to_u32("10.9.0.1")
        pools = PoolManager(None)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.9.0.0"),
                            prefix_len=24, gateway=sip,
                            dns_primary=ip_to_u32("1.1.1.1"),
                            lease_time=3600))
        with spans.armed(Tracer()) as tr:
            fleet = SlowPathFleet(
                FleetSpec.from_pool_manager(b"\x02" * 6, sip, pools),
                n_workers=1, pools=pools, mode="process")
            try:
                assert os.environ.get("BNG_TELEMETRY") == before
                # and the child DID inherit it: its per-frame histogram
                # arrives in the stats payload and merges
                from bng_tpu.control import dhcp_codec, packets

                mac = (0x02E0 << 32).to_bytes(6, "big")
                p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER,
                                             xid=1)
                frame = packets.udp_packet(
                    mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                    p.encode().ljust(320, b"\x00"))
                out = fleet.handle_batch([(0, frame)])
                assert out[0][1] is not None
                assert tr.hists[spans.WORKER].n >= 1
            finally:
                fleet.close()

    def test_trace_cli_export_chrome(self, tmp_path):
        from bng_tpu import cli

        out = tmp_path / "dora.json"
        rc = cli.main(["trace", "export", "--format", "chrome",
                       "--out", str(out), "--macs", "16",
                       "--trace-dir", str(tmp_path)])
        assert rc == 0
        d = json.load(open(out))
        xs = [e for e in d["traceEvents"] if e["ph"] == "X"]
        assert xs and all(e["dur"] > 0 for e in xs)
        # and `trace status` sees the dir
        rc = cli.main(["trace", "status", "--trace-dir", str(tmp_path)])
        assert rc == 0
