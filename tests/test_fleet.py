"""Slow-path fleet: sharding, admission, lease slices, checkpointing.

Covers the PR-3 acceptance gates with deterministic (inline-mode)
tier-1 tests — shard affinity (same MAC -> same worker, the ring
classifier's hash), DHCP-correct shedding under synthetic overload (no
REQUEST shed after OFFER, zero double-allocated leases across workers),
reply re-merge in ring order, malformed-frame isolation, drain_pending
ordering across workers, and fleet state round-tripping through the
checkpoint format — plus slow-tier process-mode smoke and the
multi-core speedup gate.
"""

import os

import pytest

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.admission import (SHED_DEADLINE, SHED_INBOX_FULL,
                                       AdmissionConfig, AdmissionController,
                                       peek_dhcp, peek_reply)
from bng_tpu.control.fleet import (FleetSpec, FleetWorker, SlowPathFleet,
                                   shard_for_frame, shard_for_mac)
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.runtime.ring import FLAG_DHCP_CTRL, FLAG_FROM_ACCESS, shard_of
from bng_tpu.utils.net import fnv1a32, ip_to_u32

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")


def make_pools(prefix_len=16, network="10.0.0.0"):
    pools = PoolManager(None)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32(network),
                        prefix_len=prefix_len, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    return pools


def make_fleet(n=4, pools=None, mode="inline", **kw):
    pools = pools if pools is not None else make_pools()
    spec_kw = {k: kw.pop(k) for k in ("slice_size", "low_watermark")
               if k in kw}
    spec = FleetSpec.from_pool_manager(SERVER_MAC, SERVER_IP, pools,
                                       **spec_kw)
    return SlowPathFleet(spec, n, pools, mode=mode, **kw), pools


def mac_of(i: int) -> bytes:
    return (0x02C0 << 32 | i).to_bytes(6, "big")


def discover(mac, xid=1):
    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def request(mac, ip, server_id, xid=2):
    p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid,
                                 requested_ip=ip, server_id=server_id)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def renew(mac, ip, xid=3):
    p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid, ciaddr=ip)
    return packets.udp_packet(mac, b"\xff" * 6, ip, SERVER_IP, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def reply_packet(frame):
    return dhcp_codec.decode(packets.decode(frame).payload)


def dora(fleet, macs, xid_base=0):
    """Full DORA for each MAC through the fleet; returns {mac: ip}."""
    out = fleet.handle_batch(
        [(i, discover(m, xid_base + i)) for i, m in enumerate(macs)])
    offers = {}
    for (lane, rep), m in zip(out, macs):
        assert rep is not None, f"no OFFER on lane {lane}"
        o = reply_packet(rep)
        assert o.msg_type == dhcp_codec.OFFER
        offers[m] = o.yiaddr
    out2 = fleet.handle_batch(
        [(i, request(m, offers[m], SERVER_IP, xid_base + 1000 + i))
         for i, m in enumerate(macs)])
    leased = {}
    for (lane, rep), m in zip(out2, macs):
        assert rep is not None, f"no ACK on lane {lane}"
        a = reply_packet(rep)
        assert a.msg_type == dhcp_codec.ACK
        leased[m] = a.yiaddr
    return leased


# ---------------------------------------------------------------------------
# shard affinity
# ---------------------------------------------------------------------------

class TestShardAffinity:
    def test_hash_is_the_ring_classifier_hash(self):
        """The fleet and the host ring must agree on owners: for a
        DHCP-control frame, shard_of() steers by FNV-1a32(src MAC) —
        bit-for-bit what the fleet uses."""
        for i in range(64):
            f = discover(mac_of(i))
            for n in (2, 3, 4, 8):
                assert shard_for_frame(f, n) == fnv1a32(f[6:12]) % n
                assert shard_for_frame(f, n) == shard_of(
                    f, FLAG_FROM_ACCESS | FLAG_DHCP_CTRL, n)
                assert shard_for_frame(f, n) == shard_for_mac(mac_of(i), n)

    def test_same_mac_lands_on_same_worker(self):
        """Deterministic affinity: the whole DORA of one subscriber is
        handled by (and its lease lives on) exactly the hash-owner."""
        fleet, _pools = make_fleet(n=4)
        macs = [mac_of(i) for i in range(48)]
        dora(fleet, macs)
        for m in macs:
            owner = shard_for_mac(m, 4)
            from bng_tpu.utils.net import mac_to_u64

            for w, worker in enumerate(fleet._inline):
                has = mac_to_u64(m) in worker.server.leases
                assert has == (w == owner), (
                    f"lease for {m.hex()} on worker {w}, owner {owner}")

    def test_worker1_degenerates_to_single(self):
        fleet, _ = make_fleet(n=1)
        leased = dora(fleet, [mac_of(i) for i in range(8)])
        assert len(set(leased.values())) == 8


# ---------------------------------------------------------------------------
# allocation correctness across workers
# ---------------------------------------------------------------------------

class TestLeaseSlices:
    def test_zero_double_allocation(self):
        """Every worker allocates only from parent-claimed slices, so
        two workers can never hand out the same address."""
        fleet, pools = make_fleet(n=4, slice_size=32, low_watermark=8)
        leased = dora(fleet, [mac_of(i) for i in range(200)])
        assert len(set(leased.values())) == 200
        # every leased ip is claimed in the PARENT pool by its worker
        pool = pools.pools[1]
        for m, ip in leased.items():
            owner = pool._allocated.get(ip, "")
            assert owner == f"fleet:w{shard_for_mac(m, 4)}", (m.hex(), owner)

    def test_slice_refill_under_pressure(self):
        """Slices smaller than the demand refill through the granter
        (the only cross-worker coordination point)."""
        fleet, _ = make_fleet(n=2, slice_size=16, low_watermark=8)
        leased = dora(fleet, [mac_of(i) for i in range(120)])
        assert len(set(leased.values())) == 120
        assert fleet.refills > 0

    def test_pool_exhaustion_stays_silent(self):
        """More clients than addresses: DISCOVERs beyond capacity go
        unanswered (the server's exhaustion contract), nothing crashes,
        and no address is handed out twice."""
        pools = make_pools(prefix_len=27)  # 30 hosts minus gateway
        fleet, _ = make_fleet(n=4, pools=pools, slice_size=8,
                              low_watermark=2)
        macs = [mac_of(i) for i in range(64)]
        out = fleet.handle_batch(
            [(i, discover(m, i)) for i, m in enumerate(macs)])
        offers = [reply_packet(r).yiaddr for _, r in out if r is not None]
        assert 0 < len(offers) <= 29
        assert len(set(offers)) == len(offers)

    def test_cross_worker_requested_ip_naks(self):
        """A REQUEST for an address outside the owner worker's granted
        slices must NAK (never half-allocate), even though the address
        is valid in the pool range."""
        fleet, _ = make_fleet(n=4)
        m = mac_of(1)
        # pick an ip granted to a DIFFERENT worker than m's owner
        owner = shard_for_mac(m, 4)
        other = (owner + 1) % 4
        foreign_ip = next(iter(
            fleet._inline[other].pools.pools[1]._free))
        out = fleet.handle_batch([(0, request(m, foreign_ip, SERVER_IP))])
        rep = reply_packet(out[0][1])
        assert rep.msg_type == dhcp_codec.NAK
        # and the address is still free on its owner
        assert foreign_ip not in fleet._inline[other].pools.pools[1]._allocated


# ---------------------------------------------------------------------------
# ordering + isolation (the demux-under-fleet satellite)
# ---------------------------------------------------------------------------

class TestOrderingAndIsolation:
    def test_replies_remerge_in_lane_order(self):
        """Lanes interleave across workers arbitrarily; the fan-in must
        return ascending lanes with each reply matching its lane's xid."""
        fleet, _ = make_fleet(n=4)
        macs = [mac_of(i) for i in range(32)]
        items = [(lane, discover(m, 7000 + lane))
                 for lane, m in enumerate(macs)]
        items.reverse()  # submission order != lane order
        out = fleet.handle_batch(items)
        assert [lane for lane, _ in out] == sorted(lane for lane, _ in out)
        for lane, rep in out:
            assert reply_packet(rep).xid == 7000 + lane

    def test_poison_frame_isolation(self):
        """One malformed frame must not kill a worker or shift any other
        lane's reply."""
        fleet, _ = make_fleet(n=4)
        macs = [mac_of(i) for i in range(8)]
        poison = [b"", b"\x00" * 7, b"\xff" * 64,
                  discover(mac_of(99))[:50]]  # truncated mid-header
        items = []
        lane = 0
        expect = {}
        for i, m in enumerate(macs):
            items.append((lane, discover(m, 500 + lane)))
            expect[lane] = 500 + lane
            lane += 1
            items.append((lane, poison[i % len(poison)]))
            lane += 1
        out = dict(fleet.handle_batch(items))
        assert len(out) == len(items)
        for ln, xid in expect.items():
            assert out[ln] is not None, f"lane {ln} lost its reply"
            assert reply_packet(out[ln]).xid == xid
        # poison lanes answered None, workers alive
        for ln in set(range(lane)) - set(expect):
            assert out[ln] is None

    def test_drain_pending_order_across_workers(self):
        """Multi-frame handlers queue extras on the demux pending list;
        the fleet merges pending frames in worker-index order
        (deterministic: gather is index-ordered), preserving each
        worker's internal order."""
        class EchoDemux:
            """Stub demux: replies inline AND queues two tagged extras
            (the PPPoE CHAP+IPCP multi-frame shape)."""

            def __init__(self, worker_id):
                self.worker_id = worker_id
                self.stats = {"handled": 0}
                self._pending = []
                self.seq = 0

            def __call__(self, frame):
                self.stats["handled"] += 1
                self.seq += 1
                tag = bytes([self.worker_id, self.seq])
                self._pending.extend([b"extra1-" + tag, b"extra2-" + tag])
                return b"inline-" + tag

            def drain_pending(self):
                out, self._pending = self._pending, []
                return out

        def factory(i, n):
            spec = FleetSpec.from_pool_manager(SERVER_MAC, SERVER_IP,
                                               make_pools())
            w = FleetWorker(spec, i, n)
            w.demux = EchoDemux(i)
            return w

        fleet, _ = make_fleet(n=3, worker_factory=factory)
        macs = [mac_of(i) for i in range(12)]
        fleet.handle_batch([(i, discover(m)) for i, m in enumerate(macs)])
        pending = fleet.drain_pending()
        assert len(pending) == 24  # 2 extras per frame
        # worker-index order, each worker's extras in its own seq order
        worker_seen = [f[7] for f in pending]  # worker_id byte
        assert worker_seen == sorted(worker_seen)
        for w in set(worker_seen):
            seqs = [f[8] for f in pending if f[7] == w]
            assert seqs == sorted(seqs)
        assert fleet.drain_pending() == []


# ---------------------------------------------------------------------------
# admission control (DHCP-correct shedding)
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_peek_helpers(self):
        f = discover(mac_of(3), xid=9)
        mt, mac = peek_dhcp(f)
        assert mt == dhcp_codec.DISCOVER
        assert mac == int.from_bytes(mac_of(3), "big")
        assert peek_dhcp(b"junk") is None
        assert peek_reply(f) is None  # BOOTREQUEST, not a reply

    def test_shed_discover_first_under_overload(self):
        """Synthetic overload: inbox bound 8. DISCOVERs past the bound
        shed; every REQUEST whose OFFER the fleet already sent is
        answered — and no lease is half-allocated."""
        fleet, _ = make_fleet(
            n=1, admission=AdmissionConfig(inbox_capacity=8))
        offered = dora(fleet, [mac_of(i) for i in range(4)])
        # overload: 40 fresh DISCOVERs + the 4 known clients' renewals
        items = [(i, discover(mac_of(100 + i), i)) for i in range(40)]
        items += [(40 + j, renew(m, ip, 9000 + j))
                  for j, (m, ip) in enumerate(offered.items())]
        out = dict(fleet.handle_batch(items))
        # every known client answered, same address (no REQUEST shed)
        for j, (m, ip) in enumerate(offered.items()):
            rep = out[40 + j]
            assert rep is not None, "REQUEST of an offered client was shed"
            a = reply_packet(rep)
            assert a.msg_type == dhcp_codec.ACK and a.yiaddr == ip
        shed = fleet.admission.stats.shed
        assert shed[SHED_INBOX_FULL] > 0
        # sheds were all DISCOVERs: answered DISCOVER count == admitted
        answered = sum(1 for i in range(40) if out[i] is not None)
        assert answered < 40
        # no half allocation: every OFFERed address is unique
        offers = {reply_packet(out[i]).yiaddr
                  for i in range(40) if out[i] is not None}
        assert len(offers) == answered

    def test_never_shed_request_after_offer_even_past_hard_cap(self):
        ctl = AdmissionController(AdmissionConfig(
            inbox_capacity=4, request_hard_capacity=8), clock=lambda: 100.0)
        mac = int.from_bytes(mac_of(7), "big")
        ctl.note_offer(mac)
        f = request(mac_of(7), ip_to_u32("10.0.0.9"), SERVER_IP)
        ok, reason = ctl.admit(f, inbox_depth=10_000, now=100.0)
        assert ok, reason
        # an UNKNOWN client's request past the hard cap does shed
        f2 = request(mac_of(8), ip_to_u32("10.0.0.10"), SERVER_IP)
        ok2, reason2 = ctl.admit(f2, inbox_depth=10_000, now=100.0)
        assert not ok2 and reason2 == "request_overflow"

    def test_deadline_sheds_stale_discover_not_request(self):
        ctl = AdmissionController(AdmissionConfig(deadline_ms=50),
                                  clock=lambda: 100.0)
        mac = int.from_bytes(mac_of(5), "big")
        ctl.note_ack(mac)
        stale = 100.0 - 0.2  # 200ms old
        ok, reason = ctl.admit(discover(mac_of(6)), 0, 100.0, enq_t=stale)
        assert not ok and reason == SHED_DEADLINE
        ok2, _ = ctl.admit(renew(mac_of(5), ip_to_u32("10.0.0.9")),
                           0, 100.0, enq_t=stale)
        assert ok2, "a known client's stale REQUEST must still be served"

    def test_fresh_traffic_admits_without_peek(self):
        """No pressure -> the fast path admits without parsing (the
        peek exists to pick WHAT to shed)."""
        ctl = AdmissionController(clock=lambda: 0.0)
        ok, _ = ctl.admit(b"not even a frame", 0, 0.0)
        assert ok
        assert ctl.stats.unparsed == 0  # peek never ran

    def test_release_and_expiry_trim_known_clients(self):
        """RELEASE has no reply frame, so worker results report ended
        leases explicitly — without that the admission controller's
        known set (and its never-shed protection) grows forever."""
        fleet, _ = make_fleet(n=2)
        macs = [mac_of(i) for i in range(6)]
        leased = dora(fleet, macs)
        assert fleet.admission.stats_snapshot()["leases_tracked"] == 6
        rel = dhcp_codec.build_request(macs[0], dhcp_codec.RELEASE,
                                      ciaddr=leased[macs[0]])
        frame = packets.udp_packet(macs[0], b"\xff" * 6, leased[macs[0]],
                                   SERVER_IP, 68, 67,
                                   rel.encode().ljust(300, b"\x00"))
        fleet.handle_batch([(0, frame)])
        assert fleet.admission.stats_snapshot()["leases_tracked"] == 5
        # expiry sweep trims the rest
        for w in fleet._inline:
            for lease in w.server.leases.values():
                lease.expiry = 0
        fleet.expire(10)
        assert fleet.admission.stats_snapshot()["leases_tracked"] == 0

    def test_leased_set_bounded(self):
        ctl = AdmissionController(AdmissionConfig(lease_cap=4),
                                  clock=lambda: 0.0)
        for i in range(10):
            ctl.note_ack(i)
        assert ctl.stats_snapshot()["leases_tracked"] == 4
        assert ctl.is_known(9) and not ctl.is_known(0)

    def test_offer_ttl_expires(self):
        t = [100.0]
        ctl = AdmissionController(AdmissionConfig(offer_ttl_s=60),
                                  clock=lambda: t[0])
        mac = int.from_bytes(mac_of(9), "big")
        ctl.note_offer(mac)
        assert ctl.is_known(mac)
        t[0] += 61
        assert not ctl.is_known(mac)


# ---------------------------------------------------------------------------
# single-writer table relay
# ---------------------------------------------------------------------------

class TestTableRelay:
    def test_events_reach_parent_tables(self):
        from bng_tpu.runtime.tables import FastPathTables

        fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(SERVER_MAC, SERVER_IP)
        fleet, _ = make_fleet(n=4, table_sink=fastpath)
        leased = dora(fleet, [mac_of(i) for i in range(24)])
        assert fastpath.sub.count == 24
        # expiry sweep relays removals the same way
        for w in fleet._inline:
            for lease in w.server.leases.values():
                lease.expiry = 0
        assert fleet.expire(10) == 24
        assert fastpath.sub.count == 0
        assert len(leased) == 24

    def test_qos_nat_and_lease_hooks_relay(self):
        qos_calls, nat_calls, lease_events = [], [], []
        fleet, _ = make_fleet(
            n=2, qos_hook=lambda ip, pol: qos_calls.append((ip, pol)),
            nat_hook=lambda ip, now: nat_calls.append((ip, now)),
            lease_hook=lambda ev, d, sid: lease_events.append(ev))
        dora(fleet, [mac_of(i) for i in range(6)])
        assert len(qos_calls) == 6 and len(nat_calls) == 6
        assert lease_events.count("start") == 6


# ---------------------------------------------------------------------------
# checkpoint / warm restart
# ---------------------------------------------------------------------------

class TestFleetCheckpoint:
    def test_export_restore_reshards_to_new_worker_count(self):
        fleet, _ = make_fleet(n=2)
        macs = [mac_of(i) for i in range(30)]
        leased = dora(fleet, macs)
        state = fleet.export_state()
        assert SlowPathFleet.parse_state(state) == 30

        fleet2, pools2 = make_fleet(n=3)
        assert fleet2.restore_state(state) == 30
        # every lease re-sharded onto its hash owner at n=3
        from bng_tpu.utils.net import mac_to_u64

        for m in macs:
            owner = shard_for_mac(m, 3)
            assert mac_to_u64(m) in fleet2._inline[owner].server.leases
        # renewals ACK the SAME address, no re-DORA
        out = fleet2.handle_batch(
            [(i, renew(m, leased[m], 100 + i)) for i, m in enumerate(macs)])
        for (lane, rep), m in zip(out, macs):
            a = reply_packet(rep)
            assert a.msg_type == dhcp_codec.ACK and a.yiaddr == leased[m]
        # and fresh DORAs can never double-assign a restored address
        fresh = dora(fleet2, [mac_of(1000 + i) for i in range(20)])
        assert not (set(fresh.values()) & set(leased.values()))

    def test_checkpoint_format_roundtrip_and_reject(self):
        from bng_tpu.runtime import checkpoint as ckpt_mod

        fleet, _ = make_fleet(n=2)
        leased = dora(fleet, [mac_of(i) for i in range(10)])
        ck = ckpt_mod.build_checkpoint(7, 123.0, fleet=fleet)
        blob = ckpt_mod.encode_checkpoint(ck)
        dec = ckpt_mod.decode_checkpoint(blob)

        fleet2, _ = make_fleet(n=2)
        rows = ckpt_mod.restore_checkpoint(dec, fleet=fleet2)
        assert rows["fleet.leases"] == 10
        out = fleet2.handle_batch(
            [(0, renew(mac_of(0), leased[mac_of(0)]))])
        assert reply_packet(out[0][1]).msg_type == dhcp_codec.ACK

        # corrupt lease book -> reject, nothing hydrated
        bad = ckpt_mod.decode_checkpoint(blob)
        import json as _json
        import numpy as _np

        meta = _json.loads(bytes(bad.arrays["fleet/__payload_json__"]))
        meta["workers"][0]["leases"][0]["mac"] = "zz"  # not hex
        bad.arrays["fleet/__payload_json__"] = _np.frombuffer(
            _json.dumps(meta).encode(), dtype=_np.uint8).copy()
        fleet3, _ = make_fleet(n=2)
        with pytest.raises(ckpt_mod.CheckpointError):
            ckpt_mod.restore_checkpoint(bad, fleet=fleet3)
        assert sum(len(w.server.leases) for w in fleet3._inline) == 0

    def test_missing_target_rejects(self):
        from bng_tpu.runtime import checkpoint as ckpt_mod

        fleet, _ = make_fleet(n=2)
        dora(fleet, [mac_of(0)])
        ck = ckpt_mod.build_checkpoint(1, 1.0, fleet=fleet)
        dec = ckpt_mod.decode_checkpoint(ckpt_mod.encode_checkpoint(ck))
        with pytest.raises(ckpt_mod.CheckpointError):
            ckpt_mod.restore_checkpoint(dec)  # neither fleet nor dhcp

    def test_fleet_checkpoint_restores_into_fleetless_process(self):
        """Turning the fleet OFF across a restart must not cold-start:
        worker lease books merge into the parent DHCP server (same
        format) and renewals keep their addresses."""
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.runtime import checkpoint as ckpt_mod

        fleet, _ = make_fleet(n=3)
        macs = [mac_of(i) for i in range(12)]
        leased = dora(fleet, macs)
        dec = ckpt_mod.decode_checkpoint(ckpt_mod.encode_checkpoint(
            ckpt_mod.build_checkpoint(1, 1.0, fleet=fleet)))

        pools = make_pools()
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools)
        rows = ckpt_mod.restore_checkpoint(dec, dhcp=server)
        assert rows["dhcp.leases"] == 12
        for i, m in enumerate(macs):
            frame = server.handle_frame(renew(m, leased[m], i))
            a = reply_packet(frame)
            assert a.msg_type == dhcp_codec.ACK and a.yiaddr == leased[m]

    def test_dhcp_checkpoint_restores_into_fleet_process(self):
        """Turning the fleet ON across a restart: the parent lease book
        re-shards into the workers; the parent book stays EMPTY (double
        ownership would let its expiry sweep release worker-held
        addresses)."""
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.runtime import checkpoint as ckpt_mod

        pools = make_pools()
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools)
        macs = [mac_of(i) for i in range(10)]
        leased = {}
        for i, m in enumerate(macs):
            off = reply_packet(server.handle_frame(discover(m, i)))
            ack = reply_packet(server.handle_frame(
                request(m, off.yiaddr, SERVER_IP, 100 + i)))
            leased[m] = ack.yiaddr
        dec = ckpt_mod.decode_checkpoint(ckpt_mod.encode_checkpoint(
            ckpt_mod.build_checkpoint(1, 1.0, dhcp=server)))

        fleet, _ = make_fleet(n=3)
        server2 = DHCPServer(SERVER_MAC, SERVER_IP, make_pools())
        rows = ckpt_mod.restore_checkpoint(dec, dhcp=server2, fleet=fleet)
        assert rows["fleet.leases"] == 10
        assert not server2.leases
        out = fleet.handle_batch(
            [(i, renew(m, leased[m], i)) for i, m in enumerate(macs)])
        for (_lane, rep), m in zip(out, macs):
            a = reply_packet(rep)
            assert a.msg_type == dhcp_codec.ACK and a.yiaddr == leased[m]


# ---------------------------------------------------------------------------
# engine integration: PASS lanes fan out, replies re-merge in ring order
# ---------------------------------------------------------------------------

def build_engine(batch=32):
    from bng_tpu.control.nat import NATManager
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.tables import FastPathTables

    # geometry matches tests/test_loadtest.build_engine so the jitted
    # programs are shared via the lru cache (no extra tier-1 compiles)
    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=16, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=86400))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    return Engine(fastpath, nat, batch_size=batch), pools, fastpath


class TestEngineFanout:
    def test_process_routes_slow_lanes_through_fleet(self):
        engine, pools, fastpath = build_engine()
        fleet, _ = make_fleet(n=4, pools=pools, table_sink=fastpath)
        engine.slow_path_batch = fleet.handle_batch
        macs = [mac_of(i) for i in range(16)]
        res = engine.process([discover(m, i) for i, m in enumerate(macs)])
        slow = dict(res["slow"])
        assert len(slow) == 16
        assert sorted(slow) == [lane for lane, _ in res["slow"]]
        offers = {}
        for i, m in enumerate(macs):
            rep = reply_packet(slow[i])
            assert rep.msg_type == dhcp_codec.OFFER
            offers[m] = rep.yiaddr
        # REQUESTs ACK through the fleet AND populate the device cache
        res2 = engine.process([request(m, offers[m], SERVER_IP, 50 + i)
                               for i, m in enumerate(macs)])
        for _lane, rep in res2["slow"]:
            assert reply_packet(rep).msg_type == dhcp_codec.ACK
        assert fastpath.sub.count == 16
        # renewals now answer ON DEVICE (tx), no slow lane at all
        res3 = engine.process([renew(m, offers[m], 90 + i)
                               for i, m in enumerate(macs)])
        assert len(res3["tx"]) == 16 and not res3["slow"]

    def test_batch_handler_failure_degrades_to_none(self):
        engine, _pools, _fp = build_engine()

        def broken(items):
            raise RuntimeError("fleet IPC down")

        engine.slow_path_batch = broken
        res = engine.process([discover(mac_of(0))])
        assert res["slow"] == [(0, None)]
        assert engine.stats.slow_errors == 1


# ---------------------------------------------------------------------------
# slow tier: process mode, speedup gate, app-level checkpoint round trip
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestProcessMode:
    def test_process_fleet_dora_and_poison_survival(self):
        fleet, _ = make_fleet(n=2, mode="process")
        try:
            macs = [mac_of(i) for i in range(32)]
            leased = dora(fleet, macs)
            assert len(set(leased.values())) == 32
            # poison mid-batch: workers must survive and keep answering
            out = dict(fleet.handle_batch(
                [(0, b"\xff" * 80), (1, discover(mac_of(500), 77)),
                 (2, b"")]))
            assert out[0] is None and out[2] is None
            assert reply_packet(out[1]).xid == 77
            out2 = fleet.handle_batch([(0, renew(mac_of(0), leased[mac_of(0)]))])
            assert reply_packet(out2[0][1]).msg_type == dhcp_codec.ACK
            # and the lease books round-trip out of live processes
            # (32 ACKed leases; mac_of(500)'s un-REQUESTed OFFER is
            # transient state and deliberately not exported)
            assert SlowPathFleet.parse_state(fleet.export_state()) == 32
        finally:
            fleet.close()

    @pytest.mark.skipif((os.cpu_count() or 1) < 4, reason=(
        "fleet speedup needs >=4 real cores: on 2-vCPU "
        "syscall-virtualized CI sandboxes the pipe ping-pong dominates "
        "and process scaling is physically unavailable (PERF_NOTES §6)"))
    def test_loadtest_workers4_doubles_single_worker_rps(self):
        """The acceptance gate: `loadtest --workers 4` >= 2x the
        single-worker slow-path req/s on CPU."""
        import time

        from bng_tpu.control.admission import AdmissionConfig

        macs = [mac_of(i) for i in range(20000)]
        frames = [discover(m, i) for i, m in enumerate(macs)]
        B = 2048

        def run(workers, secs=4.0):
            pools = make_pools(prefix_len=12)
            spec = FleetSpec.from_pool_manager(
                SERVER_MAC, SERVER_IP, pools, slice_size=4096,
                low_watermark=512)
            fleet = SlowPathFleet(
                spec, workers, pools, mode="process",
                admission=AdmissionConfig(inbox_capacity=B))
            try:
                n = i = 0
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < secs:
                    out = fleet.handle_batch(
                        [(k, frames[(i + k) % len(frames)])
                         for k in range(B)])
                    n += sum(1 for _l, r in out if r is not None)
                    i += B
                return n / (time.perf_counter() - t0)
            finally:
                fleet.close()

        single = run(1)
        quad = run(4)
        assert quad >= 2.0 * single, (
            f"fleet {quad:.0f} req/s < 2x single {single:.0f} req/s")


class TestAppCheckpointRoundTrip:
    def test_bng_checkpoint_save_restore_fleet(self, tmp_path):
        """Fleet state round-trips through the real `bng checkpoint`
        path: BNGApp snapshot -> CheckpointStore -> fresh BNGApp
        restore-at-start -> renewals ACK the same addresses. Tier-1:
        no jitted program runs, so this costs well under a second."""
        from bng_tpu.cli import BNGApp, BNGConfig

        cfg = BNGConfig(
            slowpath_workers=2, slowpath_worker_mode="inline",
            checkpoint_dir=str(tmp_path), metrics_enabled=False,
            dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False)
        app = BNGApp(cfg)
        try:
            fleet = app.components["fleet"]
            macs = [mac_of(i) for i in range(12)]
            leased = dora(fleet, macs)
            app.components["checkpointer"].save_now(reason="test")
        finally:
            app.close()

        app2 = BNGApp(cfg)
        try:
            assert "checkpoint_error" not in app2.components
            rows = app2.components["checkpoint_restored"]
            assert rows["fleet.leases"] == 12
            fleet2 = app2.components["fleet"]
            out = fleet2.handle_batch(
                [(i, renew(m, leased[m], i)) for i, m in enumerate(macs)])
            for (_lane, rep), m in zip(out, macs):
                a = reply_packet(rep)
                assert a.msg_type == dhcp_codec.ACK
                assert a.yiaddr == leased[m]
        finally:
            app2.close()
