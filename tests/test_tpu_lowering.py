"""TPU-lowering gate (auto-skips off-TPU).

The round-2 smoking gun: ops/pallas_qos passed its interpret-mode suite
while Mosaic rejected its block shapes on real hardware. This gate
AOT-compiles every hot program for the attached TPU so a kernel that
cannot lower can never ship green again. CI: `python bench.py
--verify-lowering` runs the same checks.
"""

import jax
import pytest

from bng_tpu.runtime.verify import verify_tpu_lowering

_ON_TPU = jax.default_backend() == "tpu"


@pytest.mark.skipif(not _ON_TPU, reason="Mosaic lowering needs a real TPU")
def test_all_hot_programs_lower_for_tpu():
    results = verify_tpu_lowering(verbose=True)
    failures = [(n, e) for n, e in results if e is not None]
    assert not failures, "TPU lowering failures:\n" + "\n".join(
        f"--- {n} ---\n{e}" for n, e in failures)


@pytest.mark.skipif(_ON_TPU, reason="redundant on TPU: the full gate runs")
# tier-1 budget: ~47s compiling the whole lowering-gate harness on CPU
# — slow tier (verify-slow/verify-all); bench.py --verify-lowering and
# runtime/verify.py subsets still gate lowering in their own targets
@pytest.mark.slow
def test_gate_harness_compiles_on_any_backend():
    """The non-Mosaic checks must compile everywhere, so harness API drift
    (round 3: a stale NATManager signature broke the gate itself) is caught
    by the plain CPU suite, not discovered on the bench chip."""
    results = verify_tpu_lowering(verbose=False, tpu=False)
    failures = [(n, e) for n, e in results if e is not None]
    assert not failures, "gate harness failures:\n" + "\n".join(
        f"--- {n} ---\n{e}" for n, e in failures)
