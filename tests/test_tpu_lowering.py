"""TPU-lowering gate (auto-skips off-TPU).

The round-2 smoking gun: ops/pallas_qos passed its interpret-mode suite
while Mosaic rejected its block shapes on real hardware. This gate
AOT-compiles every hot program for the attached TPU so a kernel that
cannot lower can never ship green again. CI: `python bench.py
--verify-lowering` runs the same checks.
"""

import jax
import pytest

from bng_tpu.runtime.verify import verify_tpu_lowering

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="TPU-lowering gate needs a real TPU target (Mosaic is TPU-only)",
)


def test_all_hot_programs_lower_for_tpu():
    results = verify_tpu_lowering(verbose=True)
    failures = [(n, e) for n, e in results if e is not None]
    assert not failures, "TPU lowering failures:\n" + "\n".join(
        f"--- {n} ---\n{e}" for n, e in failures)
