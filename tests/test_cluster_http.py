"""HTTP/SSE cluster transports: HA sync, peer pool, CRDT, Nexus allocator.

Round-2 verdict missing #2's done-criteria: two processes fail over and
keep sessions; a peer pool forwards an allocate to the HRW owner over
HTTP. These tests run real TCP servers (loopback); the final test runs a
genuinely separate python process.
"""

import json
import subprocess
import sys
import time

import pytest

from bng_tpu.control.cluster_http import (
    ClusterServer, HTTPActiveProxy, HTTPPeerProxy, HTTPStorePeer,
    http_nexus_transport,
)
from bng_tpu.control.crdt import CLSetStore, DistributedStore, MODE_WRITE
from bng_tpu.control.ha import (
    ActiveSyncer, InMemorySessionStore, SessionState, StandbySyncer,
)
from bng_tpu.control.nexus import HTTPAllocator
from bng_tpu.control.peerpool import PeerPool, PoolRange


def wait_until(pred, timeout=5.0, step=0.02):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return False


@pytest.fixture
def server():
    servers = []

    def make() -> ClusterServer:
        s = ClusterServer().start()
        servers.append(s)
        return s

    yield make
    for s in servers:
        s.close()


class TestHASyncOverHTTP:
    def test_full_sync_deltas_and_failover(self, server):
        active_store = InMemorySessionStore()
        active = ActiveSyncer(active_store)
        srv = server().mount_ha(active)

        active.push_change(SessionState("s1", mac="02:00:00:00:00:01",
                                        ip=0x0A000001))
        active.push_change(SessionState("s2", mac="02:00:00:00:00:02",
                                        ip=0x0A000002))

        standby_store = InMemorySessionStore()
        standby = StandbySyncer(standby_store, transport=lambda: HTTPActiveProxy(
            srv.url, on_stream_end=lambda: standby.disconnect()))
        standby.tick(now=0.0)
        assert standby.connected
        assert len(standby_store) == 2  # full sync over the wire

        # live SSE delta
        active.push_change(SessionState("s3", ip=0x0A000003))
        active.push_change(None, session_id="s1")
        assert wait_until(lambda: len(standby_store) == 2 and
                          standby_store.get("s3") is not None)
        assert standby_store.get("s1") is None

        # --- active dies: stream ends, standby reconnect-backoffs, and
        # the replicated sessions survive for promotion ---
        srv.close()
        assert wait_until(lambda: not standby.connected)
        standby.tick(now=100.0)  # reconnect attempt fails
        assert not standby.connected
        assert standby_store.get("s3").ip == 0x0A000003  # sessions kept

    def test_replay_gap_forces_full_resync(self, server):
        active = ActiveSyncer(InMemorySessionStore(), replay_buffer=4)
        srv = server().mount_ha(active)
        store = InMemorySessionStore()
        standby = StandbySyncer(store, transport=lambda: HTTPActiveProxy(srv.url))
        standby.tick(now=0.0)
        standby.disconnect()
        for i in range(20):  # overflow the replay buffer
            active.push_change(SessionState(f"s{i}", ip=i))
        standby.tick(now=50.0)
        assert standby.connected
        assert len(store) == 20 and standby.stats["full_syncs"] == 2


class TestPeerPoolOverHTTP:
    def test_forward_allocate_to_hrw_owner(self, server):
        """The verdict's literal done-criterion for the peer pool."""
        nodes = ["n1", "n2"]
        pool_def = PoolRange(network=0x0A640000, size=1000)
        proxies = {}

        def transport(node):
            return HTTPPeerProxy(proxies[node])

        p1 = PeerPool("n1", nodes, pool_def, transport=transport)
        p2 = PeerPool("n2", nodes, pool_def, transport=transport)
        s1 = server().mount_pool(p1)
        s2 = server().mount_pool(p2)
        proxies.update(n1=s1.url, n2=s2.url)

        # find a subscriber id each node does NOT own -> real HTTP forward
        sub_owned_by_2 = next(s for s in (f"sub{i}" for i in range(100))
                              if p1.owner_ranked(s)[0] == "n2")
        ip = p1.allocate(sub_owned_by_2)
        assert p1.stats["forwarded"] == 1 and p2.stats["local_allocs"] == 1
        assert p2.by_subscriber[sub_owned_by_2] == ip
        # read side: n1 resolves it via the owner over HTTP
        assert p1.get(sub_owned_by_2) == ip
        # release over HTTP
        assert p1.release(sub_owned_by_2)
        assert sub_owned_by_2 not in p2.by_subscriber

    def test_owner_down_fails_over_to_next_ranked(self, server):
        nodes = ["n1", "n2"]
        pool_def = PoolRange(network=0x0A640000, size=100)
        urls = {}

        def transport(node):
            if node not in urls:
                raise ConnectionError(f"{node} down")
            return HTTPPeerProxy(urls[node])

        p1 = PeerPool("n1", nodes, pool_def, transport=transport)
        sub = next(s for s in (f"sub{i}" for i in range(100))
                   if p1.owner_ranked(s)[0] == "n2")
        ip = p1.allocate(sub)  # n2 unreachable -> local failover allocation
        assert p1.stats["failovers"] >= 1
        assert p1.by_subscriber[sub] == ip


class TestCRDTOverHTTP:
    def test_anti_entropy_over_the_wire(self, server):
        a = DistributedStore("a", mode=MODE_WRITE)
        b = DistributedStore("b", mode=MODE_WRITE)
        srv_b = server().mount_store(b)
        a.add_peer(HTTPStorePeer(srv_b.url))

        a.put("sub/1", b"ip=10.0.0.1")
        b.put("sub/2", b"ip=10.0.0.2")
        b.delete("sub/2")
        b.put("sub/3", b"\x00\x01\xff")  # binary-safe

        a.tick()  # one HTTP anti-entropy round, both directions
        assert a.get("sub/3") == b"\x00\x01\xff"
        assert a.get("sub/2") is None
        assert b.get("sub/1") == b"ip=10.0.0.1"
        assert a.store.digest() == b.store.digest()

    def test_unreachable_peer_skipped(self):
        a = DistributedStore("a", mode=MODE_WRITE)
        a.add_peer(HTTPStorePeer("http://127.0.0.1:1"))  # nothing listens
        a.put("k", b"v")
        assert a.tick() == 0  # no exception, round skipped


class TestNexusAllocatorOverHTTP:
    def test_allocate_lookup_release(self, server):
        class Backend:
            def __init__(self):
                self.ips = {}

            def allocate(self, subscriber_id, pool_hint):
                ip = self.ips.setdefault(subscriber_id,
                                         f"10.9.0.{len(self.ips) + 1}")
                return ip

            def lookup(self, subscriber_id):
                return self.ips.get(subscriber_id)

            def release(self, subscriber_id):
                return self.ips.pop(subscriber_id, None) is not None

            def pool_info(self):
                return {"pools": [{"id": "p1", "used": len(self.ips)}]}

        srv = server().mount_allocator(Backend())
        alloc = HTTPAllocator(srv.url, http_nexus_transport(srv.url))
        ip = alloc.allocate("subA")
        assert ip == "10.9.0.1"
        assert alloc.lookup("subA") == ip
        assert alloc.health_check()
        assert alloc.get_pool_info()["pools"][0]["used"] == 1
        assert alloc.release("subA")
        assert alloc.lookup("subA") is None


class TestTwoProcesses:
    def test_real_second_process_syncs_sessions(self, server, tmp_path):
        """An actually-separate python process full-syncs and receives SSE
        deltas from this process's active syncer."""
        active = ActiveSyncer(InMemorySessionStore())
        srv = server().mount_ha(active)
        active.push_change(SessionState("boot", ip=1))

        code = f"""
import json, sys, time
from bng_tpu.control.cluster_http import HTTPActiveProxy
from bng_tpu.control.ha import InMemorySessionStore, StandbySyncer
store = InMemorySessionStore()
sb = StandbySyncer(store, transport=lambda: HTTPActiveProxy({srv.url!r}))
sb.tick(now=0.0)
t0 = time.time()
while time.time() - t0 < 10:
    if store.get("live") is not None:
        print(json.dumps({{"n": len(store), "live_ip": store.get("live").ip}}))
        sys.exit(0)
    time.sleep(0.05)
sys.exit(2)
"""
        import os

        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}  # child must never claim the TPU
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE, text=True, env=env)
        time.sleep(1.0)  # child is full-synced and streaming by now
        active.push_change(SessionState("live", ip=0x7F000001))
        out, _ = proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        got = json.loads(out.strip().splitlines()[-1])
        assert got == {"n": 2, "live_ip": 0x7F000001}


class TestStreamRobustness:
    def test_fresh_active_seq0_window_not_lost(self, server):
        """Deltas between a seq-0 full sync and the stream connect must be
        replayed (code-review r3 finding: the since==0 guard dropped them)."""
        active = ActiveSyncer(InMemorySessionStore())
        srv = server().mount_ha(active)
        store = InMemorySessionStore()
        standby = StandbySyncer(store, transport=lambda: HTTPActiveProxy(srv.url))
        # full-sync a FRESH active (seq 0)...
        proxy = HTTPActiveProxy(srv.url)
        sessions, seq = proxy.full_sync()
        assert seq == 0
        # ...a session lands in the sync->subscribe window...
        active.push_change(SessionState("gap", ip=42))
        # ...then the stream opens with since=0 and must replay it
        got = []
        cancel = proxy.subscribe(got.append)
        assert wait_until(lambda: len(got) == 1)
        assert got[0].session.session_id == "gap"
        cancel()

    def test_slow_consumer_never_crashes_active(self, server):
        """4096+ undelivered deltas end the stream, not the active
        (code-review r3 finding: put_nowait raised into push_change)."""
        import urllib.request

        active = ActiveSyncer(InMemorySessionStore())
        srv = server().mount_ha(active)
        # open a stream and never read it
        conn = urllib.request.urlopen(f"{srv.url}/ha/stream?since=0", timeout=10)
        time.sleep(0.2)
        for i in range(5000):  # overflows the 4096 SSE queue
            active.push_change(SessionState(f"s{i}", ip=i))
        # the active survived and kept every session
        assert len(active.store.all()) == 5000
        conn.close()
