"""Load harness: warmup populates the cache, measurement steers the
fast/slow split by MAC cardinality, targets gate (test/load parity)."""

import numpy as np

from bng_tpu.control.dhcp_server import DHCPServer
from bng_tpu.control.nat import NATManager
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.loadtest import BenchmarkConfig, BenchmarkResult, DHCPBenchmark
from bng_tpu.runtime.engine import Engine
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.utils.net import ip_to_u32

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")


def build_engine(batch=32):
    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=16, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=86400))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools, fastpath_tables=fastpath)
    return Engine(fastpath, nat, batch_size=batch,
                  slow_path=server.handle_frame)


class TestWarmup:
    def test_warmup_leases_all_macs(self):
        engine = build_engine()
        cfg = BenchmarkConfig(batch_size=32, unique_macs=48, warmup_s=60.0)
        bench = DHCPBenchmark(engine, cfg)
        leased = bench.warmup()
        assert leased == 48
        # every lease landed in the device cache
        assert engine.fastpath.sub.count == 48


class TestMeasurement:
    def test_renewals_hit_fast_path(self):
        engine = build_engine()
        cfg = BenchmarkConfig(batch_size=32, unique_macs=32, warmup_s=60.0,
                              duration_s=0.5, renewal_ratio=1.0)
        bench = DHCPBenchmark(engine, cfg)
        res = bench.run()
        assert res.requests > 0
        assert res.responses > 0
        # all measured traffic targets leased MACs -> device cache hits
        assert res.cache_hit_rate > 0.95
        assert res.fastpath_hits > 0
        assert res.latency_p99_us >= res.latency_p50_us > 0

    def test_cold_macs_go_slow_path(self):
        engine = build_engine()
        # no renewals and a much larger MAC space than the warmup covers
        cfg = BenchmarkConfig(batch_size=32, unique_macs=256, warmup_s=0.0,
                              duration_s=0.3, enable_renewals=False)
        bench = DHCPBenchmark(engine, cfg)
        res = bench.run()
        assert res.slowpath_hits > 0
        # server answered the slow-path lanes
        assert res.responses > 0


class TestTargets:
    def test_meets_targets_gating(self):
        cfg = BenchmarkConfig()
        good = BenchmarkResult(rps=60_000, latency_p99_us=5_000,
                               cache_hit_rate=0.97)
        assert good.meets_targets(cfg) == []
        bad = BenchmarkResult(rps=10_000, latency_p99_us=50_000,
                              cache_hit_rate=0.5)
        failures = bad.meets_targets(cfg)
        assert len(failures) == 3

    def test_result_serializes(self):
        from bng_tpu.loadtest import result_json

        res = BenchmarkResult(rps=1.0)
        assert '"rps": 1.0' in result_json(res)


class TestCLI:
    def test_loadtest_subcommand(self, capsys):
        from bng_tpu.cli import main

        rc = main(["loadtest", "--duration", "0.2", "--warmup", "5",
                   "--batch-size", "32", "--macs", "32", "--json"])
        assert rc == 0
        out = capsys.readouterr().out
        import json

        data = json.loads(out)
        assert data["requests"] > 0
