"""Runtime sanitizer tests (BNG_SANITIZE, bng_tpu/analysis/sanitize.py).

The sanitizer is the dynamic cross-check of bngcheck's static transfer
lint: transfer guards + debug_nans armed around hot-path code. The
planted-violation test proves the guard has real teeth on THIS backend
(an implicit transfer into a jitted call raises); the caveat test pins
the measured XLA:CPU asymmetry the docs promise (d2h guards inert,
h2d guards live), so a jaxlib upgrade that changes guard behavior
fails loudly here instead of silently changing what `make
verify-sanitize` covers.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bng_tpu.analysis import sanitize

pytestmark = pytest.mark.analysis


@pytest.fixture(scope="module")
def add_one():
    f = jax.jit(lambda a: a + 1)
    f(jnp.zeros(4, jnp.float32))  # compiled outside any guard
    return f


class TestPlantedViolations:
    def test_implicit_h2d_transfer_caught(self, add_one):
        """THE planted implicit transfer: a raw numpy array fed to a
        jitted step is an implicit host->device transfer and must trip
        the strict guard."""
        with sanitize.sanitized(h2d="disallow"):
            with pytest.raises(Exception, match="[Dd]isallowed"):
                add_one(np.zeros(4, np.float32))

    def test_explicit_staging_passes(self, add_one):
        """The engine's idiom — explicit jnp.asarray staging — is legal
        under the same strict guard."""
        staged = jnp.asarray(np.ones(4, np.float32))
        with sanitize.sanitized(h2d="disallow"):
            out = add_one(staged)
        assert jax.device_get(out).tolist() == [2.0] * 4

    def test_debug_nans_catches_planted_nan(self):
        with sanitize.sanitized():
            with pytest.raises(FloatingPointError):
                jax.block_until_ready(jnp.log(-jnp.ones(2)))

    def test_guards_disarmed_outside_context(self, add_one):
        # after the context exits, implicit transfers work again
        with sanitize.sanitized(h2d="disallow"):
            pass
        out = add_one(np.zeros(4, np.float32))
        assert jax.device_get(out).tolist() == [1.0] * 4


class TestCpuCaveat:
    """Pin the measured jaxlib-0.4.37 XLA:CPU behavior the sanitizer
    docs document: d2h guards never fire on CPU (so the retire path's
    np.asarray/device_get forces are safe under BNG_SANITIZE=1), while
    explicit forces stay legal everywhere."""

    @pytest.mark.skipif(jax.default_backend() != "cpu",
                        reason="pins the CPU-backend caveat")
    def test_d2h_forces_pass_on_cpu(self, add_one):
        x = add_one(jnp.zeros(4, jnp.float32))
        with sanitize.sanitized():
            assert np.asarray(x).shape == (4,)      # explicit (device_get)
            assert jax.device_get(x).shape == (4,)
            assert float(x.sum()) == 4.0            # inert on CPU

    def test_enabled_flag_parsing(self, monkeypatch):
        for val, want in (("1", True), ("true", True), ("strict", True),
                          ("0", False), ("", False)):
            monkeypatch.setenv(sanitize.SANITIZE_ENV, val)
            assert sanitize.enabled() is want
        monkeypatch.setenv(sanitize.SANITIZE_ENV, "strict")
        assert sanitize.strict()
        monkeypatch.setenv(sanitize.SANITIZE_ENV, "1")
        assert not sanitize.strict()


class TestFixtureWiring:
    """Prove the conftest autouse fixture actually arms around
    hotpath-marked tests when BNG_SANITIZE=1 (debug_nans is the
    observable: jax.config.jax_debug_nans flips inside the test)."""

    @pytest.mark.hotpath
    def test_hotpath_marked_test_is_armed_when_enabled(self):
        if sanitize.enabled():
            assert jax.config.jax_debug_nans is True
        else:
            assert jax.config.jax_debug_nans is False

    def test_unmarked_test_is_never_armed(self):
        assert jax.config.jax_debug_nans is False
