"""Tests for NAT ALGs and RFC 6908 compliance logging."""

import gzip
import json

import pytest

from bng_tpu.control.nat import (
    LOG_PORT_BLOCK_ASSIGN, LOG_SESSION_CREATE, LOG_SESSION_DELETE,
    NATLogEntry, NATManager,
)
from bng_tpu.control.nat_alg import (
    ALGConnection, ALGHandler, FTPALG, FTP_PORT, SIPALG, SIP_PORT,
)
from bng_tpu.control.nat_logging import (
    NATComplianceLogger, NATLoggerConfig,
)
from bng_tpu.utils.net import ip_to_u32


class StaticMapper:
    """Maps any (ip, port) to a fixed public IP with port+1000."""

    def __init__(self, public_ip="203.0.113.1", fail=False):
        self.public_ip = public_ip
        self.fail = fail
        self.calls = []

    def __call__(self, ip, port):
        self.calls.append((ip, port))
        if self.fail:
            return None
        return self.public_ip, port + 1000


CONN = ALGConnection(private_ip="100.64.0.5", private_port=21,
                     public_ip="203.0.113.1", public_port=2021)


class TestFTPALG:
    def test_port_command_rewritten(self):
        alg = FTPALG(StaticMapper())
        data = b"USER x\r\nPORT 100,64,0,5,19,137\r\n"  # port 5001
        out = alg.process_outbound(CONN, data)
        # 5001 + 1000 = 6001 = 23*256 + 113
        assert b"PORT 203,0,113,1,23,113" in out
        assert b"USER x" in out
        assert alg.stats["port_rewrites"] == 1

    def test_foreign_ip_untouched(self):
        alg = FTPALG(StaticMapper())
        data = b"PORT 10,9,9,9,19,137\r\n"  # not the NAT'd client
        assert alg.process_outbound(CONN, data) == data

    def test_eprt_rewritten(self):
        alg = FTPALG(StaticMapper())
        out = alg.process_outbound(CONN, b"EPRT |1|100.64.0.5|5001|\r\n")
        assert b"EPRT |1|203.0.113.1|6001|" in out

    def test_pasv_response_rewritten_inbound(self):
        alg = FTPALG(StaticMapper())
        data = b"227 Entering Passive Mode (100,64,0,5,19,137)\r\n"
        out = alg.process_inbound(CONN, data)
        assert b"(203,0,113,1,23,113)" in out

    def test_epsv_creates_mapping_only(self):
        mapper = StaticMapper()
        alg = FTPALG(mapper)
        data = b"229 Entering Extended Passive Mode (|||5005|)\r\n"
        assert alg.process_inbound(CONN, data) == data
        assert mapper.calls == [("100.64.0.5", 5005)]
        assert alg.stats["epsv_mappings"] == 1

    def test_mapper_failure_leaves_payload(self):
        alg = FTPALG(StaticMapper(fail=True))
        data = b"PORT 100,64,0,5,19,137\r\n"
        assert alg.process_outbound(CONN, data) == data
        assert alg.stats["failures"] == 1


class TestSIPALG:
    def test_outbound_headers_and_sdp(self):
        mapper = StaticMapper()
        alg = SIPALG(mapper)
        msg = (b"INVITE sip:bob@example.com SIP/2.0\r\n"
               b"Via: SIP/2.0/UDP 100.64.0.5:5060\r\n"
               b"Contact: <sip:alice@100.64.0.5:5060>\r\n"
               b"\r\n"
               b"o=- 1 1 IN IP4 100.64.0.5\r\n"
               b"c=IN IP4 100.64.0.5\r\n"
               b"m=audio 49170 RTP/AVP 0\r\n")
        out = alg.process_outbound(CONN, msg)
        assert b"100.64.0.5" not in out
        assert out.count(b"203.0.113.1") == 4
        assert ("100.64.0.5", 49170) in mapper.calls  # RTP pre-mapped

    def test_inbound_reverses(self):
        alg = SIPALG()
        msg = b"SIP/2.0 200 OK\r\nContact: <sip:bob@203.0.113.1:5060>\r\n"
        out = alg.process_inbound(CONN, msg)
        assert b"100.64.0.5" in out and b"203.0.113.1" not in out


class TestALGHandler:
    def test_dispatch_by_port(self):
        h = ALGHandler(StaticMapper())
        assert h.ports() == [FTP_PORT, SIP_PORT]
        out = h.process(CONN, FTP_PORT, b"PORT 100,64,0,5,19,137\r\n", True)
        assert b"203,0,113,1" in out
        # unknown port passes through
        data = b"GET / HTTP/1.1\r\n"
        assert h.process(CONN, 80, data, True) == data


class TestComplianceLogging:
    def _entry(self, event, t=1000, priv_port=5000, pub_port=4096,
               dest_port=443):
        return NATLogEntry(
            timestamp=t, event_type=event, subscriber_id=7,
            private_ip=ip_to_u32("100.64.0.5"),
            public_ip=ip_to_u32("203.0.113.1"),
            private_port=priv_port, public_port=pub_port,
            dest_ip=ip_to_u32("93.184.216.34"), dest_port=dest_port,
            protocol=6)

    def test_json_format_and_flush(self, tmp_path):
        path = str(tmp_path / "nat.log")
        log = NATComplianceLogger(NATLoggerConfig(file_path=path,
                                                  buffer_size=2))
        log.log_device_event(self._entry(LOG_SESSION_CREATE))
        assert log.get_stats()["buffer_used"] == 1
        log.log_device_event(self._entry(LOG_SESSION_DELETE, t=1100))
        # buffer_size=2 -> auto-flush
        lines = [json.loads(x) for x in open(path)]
        assert lines[0]["event"] == "session_create"
        assert lines[0]["public_ip"] == "203.0.113.1"
        assert lines[1]["event"] == "session_delete"
        log.close()

    @pytest.mark.parametrize("fmt,needle", [
        ("syslog", b"NAT session_create: subscriber=7"),
        ("csv", b"session_create,7,100.64.0.5,5000"),
        ("nel", b'"type":"NAT"'),
    ])
    def test_other_formats(self, tmp_path, fmt, needle):
        path = str(tmp_path / f"nat.{fmt}")
        log = NATComplianceLogger(NATLoggerConfig(file_path=path, fmt=fmt))
        log.log_device_event(self._entry(LOG_SESSION_CREATE))
        log.close()
        assert needle in open(path, "rb").read()

    def test_lea_query_by_session(self):
        log = NATComplianceLogger()
        log.log_device_event(self._entry(LOG_SESSION_CREATE, t=1000))
        log.log_device_event(self._entry(LOG_SESSION_DELETE, t=2000))
        hit = log.query_by_public_endpoint("203.0.113.1", 4096, 1500)
        assert hit and hit["private_ip"] == "100.64.0.5" and hit["subscriber"] == 7
        assert log.query_by_public_endpoint("203.0.113.1", 4096, 2500) is None
        assert log.query_by_public_endpoint("203.0.113.1", 9999, 1500) is None

    def test_bulk_logging_block_records(self, tmp_path):
        path = str(tmp_path / "nat.log")
        log = NATComplianceLogger(NATLoggerConfig(file_path=path,
                                                  bulk_logging=True))
        # sessions suppressed in bulk mode; blocks logged
        log.log_device_event(self._entry(LOG_SESSION_CREATE))
        log.log_allocation(7, "100.64.0.5", "203.0.113.1", 4096, 5119)
        log.close()
        lines = [json.loads(x) for x in open(path)]
        assert len(lines) == 1
        assert lines[0]["event"] == "port_block_assign"
        assert lines[0]["port_end"] == 5119

    def test_lea_query_by_block(self):
        clk = [1000.0]
        log = NATComplianceLogger(NATLoggerConfig(bulk_logging=True),
                                  clock=lambda: clk[0])
        log.log_allocation(7, "100.64.0.5", "203.0.113.1", 4096, 5119)
        clk[0] = 3000.0
        log.log_allocation(7, "100.64.0.5", "203.0.113.1", 4096, 5119,
                           release=True)
        hit = log.query_by_public_endpoint("203.0.113.1", 4500, 2000)
        assert hit and hit["event"] == "port_block"
        assert hit["private_ip"] == "100.64.0.5"
        assert log.query_by_public_endpoint("203.0.113.1", 4500, 3500) is None

    def test_rotation_with_gzip(self, tmp_path):
        path = str(tmp_path / "nat.log")
        log = NATComplianceLogger(NATLoggerConfig(
            file_path=path, buffer_size=1, max_file_size=200))
        for i in range(10):
            log.log_device_event(self._entry(LOG_SESSION_CREATE, t=1000 + i))
        log.close()
        gz = [f for f in tmp_path.iterdir() if f.suffix == ".gz"]
        assert gz, "rotation should produce gzipped archives"
        with gzip.open(gz[0]) as f:
            assert b"session_create" in f.read()
        assert log.get_stats()["rotations"] >= 1

    def test_age_cleanup(self, tmp_path):
        import os
        path = str(tmp_path / "nat.log")
        clk = [1000.0]
        log = NATComplianceLogger(NATLoggerConfig(
            file_path=path, max_age=100.0, compress=False), clock=lambda: clk[0])
        old = path + ".20260101-000000.0"
        open(old, "w").write("x")
        os.utime(old, (500, 500))
        clk[0] = 1_000_000.0
        # mtime 500 is way past max_age relative to wall clock? clean uses
        # file mtime vs clock - max_age
        assert log.clean_old_logs() == 1
        log.close()

    def test_nat_manager_integration(self):
        """Device punts new flow -> NATManager allocates -> logger records
        -> LEA query answers."""
        log = NATComplianceLogger()
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64,
                         log_sink=log.log_device_event)
        priv = ip_to_u32("100.64.0.5")
        nat.allocate_nat(priv, now=1000)
        verdict = nat.handle_new_flow(priv, ip_to_u32("93.184.216.34"),
                                      40000, 443, 6, pkt_len=64, now=1000)
        assert verdict is not None
        _, pub_port = verdict
        hit = log.query_by_public_endpoint("203.0.113.1", int(pub_port), 1000)
        assert hit is not None
