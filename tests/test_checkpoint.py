"""Checkpoint/warm-restart subsystem tests.

Fast tier: binary format gates (truncation, checksum, schema), the
versioned store's atomic-rename/fallback/prune behavior, host-mirror
round trips without a device program, HA bootstrap-then-replay, the
periodic cadence (including the never-raise failure path), and the
vectorized NAT expiry sweep.

Slow tier (-m slow / make verify-slow): the full engine round trip —
DORA + NAT flow through the fused pipeline, snapshot at the quiesce
barrier, restore into a FRESH engine, and fast-path parity with zero
slow-path DHCP exchanges.
"""

import json
import struct

import numpy as np
import pytest

from bng_tpu.control.dhcp_server import DHCPServer, Lease
from bng_tpu.control.ha import (ActiveSyncer, InMemorySessionStore,
                                SessionState, StandbySyncer)
from bng_tpu.control.nat import (ICMP_TIMEOUT_S, NATManager,
                                 TCP_EST_TIMEOUT_S, TCP_TRANSIENT_TIMEOUT_S,
                                 UDP_TIMEOUT_S)
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.control.statestore import CheckpointStore, PeriodicCheckpointer
from bng_tpu.ops.nat44 import (NAT_STATE_CLOSING, SV_LAST_SEEN, SV_PROTO,
                               SV_STATE)
from bng_tpu.ops.parse import PROTO_ICMP, PROTO_TCP, PROTO_UDP
from bng_tpu.runtime.checkpoint import (MAGIC, Checkpoint, CheckpointError,
                                        build_checkpoint, decode_checkpoint,
                                        encode_checkpoint,
                                        restore_checkpoint)
from bng_tpu.runtime.tables import FastPathTables, PPPoEFastPathTables
from bng_tpu.utils.net import ip_to_u32, mac_to_u64, parse_mac

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")
T0 = 1_753_000_000


class FakeClock:
    def __init__(self, t=T0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _sample_ckpt():
    return Checkpoint(
        meta={"seq": 7, "created_at": 123.5, "node_id": "n1",
              "components": {}},
        arrays={"a": np.arange(12, dtype=np.uint32).reshape(3, 4),
                "b": np.ones((5,), dtype=np.uint8)})


def _patch_header(data: bytes, **fields) -> bytes:
    """Re-write header fields (forging schema versions etc.) keeping the
    payload bytes identical; the header CRC is recomputed so only the
    forged FIELD trips validation, not the checksum."""
    import zlib

    hlen, _ = struct.unpack_from("<II", data, len(MAGIC))
    start = len(MAGIC) + 8
    hdr = json.loads(data[start : start + hlen])
    hdr.update(fields)
    new = json.dumps(hdr, separators=(",", ":")).encode()
    return data[: len(MAGIC)] \
        + struct.pack("<II", len(new), zlib.crc32(new) & 0xFFFFFFFF) \
        + new + data[start + hlen :]


class TestFormat:
    def test_roundtrip(self):
        ck = _sample_ckpt()
        got = decode_checkpoint(encode_checkpoint(ck))
        assert got.meta == ck.meta
        assert got.seq == 7
        assert np.array_equal(got.arrays["a"], ck.arrays["a"])
        assert got.arrays["a"].dtype == np.uint32
        assert np.array_equal(got.arrays["b"], ck.arrays["b"])

    def test_bad_magic_rejected(self):
        data = b"NOTACKPT" + encode_checkpoint(_sample_ckpt())[8:]
        with pytest.raises(CheckpointError, match="magic"):
            decode_checkpoint(data)

    def test_truncated_payload_rejected(self):
        data = encode_checkpoint(_sample_ckpt())
        with pytest.raises(CheckpointError, match="truncated"):
            decode_checkpoint(data[:-5])

    def test_bad_checksum_rejected(self):
        data = bytearray(encode_checkpoint(_sample_ckpt()))
        data[-1] ^= 0xFF  # flip a payload byte
        with pytest.raises(CheckpointError, match="crc32"):
            decode_checkpoint(bytes(data))

    def test_wrong_schema_version_rejected(self):
        data = _patch_header(encode_checkpoint(_sample_ckpt()),
                             schema_version=99)
        with pytest.raises(CheckpointError, match="schema version 99"):
            decode_checkpoint(data)

    def test_header_bitflip_rejected(self):
        """The header carries seq/geometry — a flipped digit there must
        trip the header CRC, not restore silently-wrong state."""
        data = bytearray(encode_checkpoint(_sample_ckpt()))
        data[len(MAGIC) + 8 + 5] ^= 0x01  # inside the header JSON
        with pytest.raises(CheckpointError, match="header crc32"):
            decode_checkpoint(bytes(data))


class TestStore:
    def test_versioned_save_and_latest(self, tmp_path):
        st = CheckpointStore(tmp_path)
        assert st.next_seq() == 1
        ck1 = _sample_ckpt()
        ck1.meta["seq"] = 1
        p1 = st.save(ck1)
        ck2 = _sample_ckpt()
        ck2.meta["seq"] = 2
        ck2.arrays["a"] = ck2.arrays["a"] + 1
        st.save(ck2)
        assert st.next_seq() == 3
        got, path = st.load_latest()
        assert got.seq == 2
        assert np.array_equal(got.arrays["a"], ck2.arrays["a"])
        assert p1.exists()  # older versions retained until prune
        # no stray temp files after atomic rename
        assert not list(tmp_path.glob(".tmp-*"))

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        st = CheckpointStore(tmp_path)
        ck1 = _sample_ckpt()
        ck1.meta["seq"] = 1
        st.save(ck1)
        ck2 = _sample_ckpt()
        ck2.meta["seq"] = 2
        p2 = st.save(ck2)
        raw = bytearray(p2.read_bytes())
        raw[-1] ^= 0xFF
        p2.write_bytes(bytes(raw))
        got, path = st.load_latest()
        assert got.seq == 1  # torn newest degraded, not fatal
        infos = st.list()
        assert infos[0].error is not None and "crc32" in infos[0].error
        assert infos[1].error is None

    def test_all_corrupt_raises_clearly(self, tmp_path):
        st = CheckpointStore(tmp_path)
        p = st.save(_sample_ckpt())
        p.write_bytes(b"garbage")
        with pytest.raises(CheckpointError, match="no restorable"):
            st.load_latest()
        with pytest.raises(CheckpointError, match="no checkpoints"):
            CheckpointStore(tmp_path / "empty").load_latest()

    def test_stray_filename_ignored(self, tmp_path):
        """A hand-copied `ckpt-latest.bngckpt` must not shadow the real
        newest file or collapse next_seq to 0."""
        st = CheckpointStore(tmp_path)
        ck = _sample_ckpt()
        ck.meta["seq"] = 3
        p = st.save(ck)
        (tmp_path / "ckpt-latest.bngckpt").write_bytes(p.read_bytes())
        assert st.next_seq() == 4
        got, path = st.load_latest()
        assert path == p
        assert [i.seq for i in st.list()] == [3]

    def test_prune_keeps_newest(self, tmp_path):
        st = CheckpointStore(tmp_path)
        for seq in range(1, 6):
            ck = _sample_ckpt()
            ck.meta["seq"] = seq
            st.save(ck)
        assert st.prune(keep=2) == 3
        assert [i.seq for i in st.list()] == [5, 4]


def _mk_stack(clock=None, sub_nbuckets=256):
    fp = FastPathTables(sub_nbuckets=sub_nbuckets, vlan_nbuckets=64,
                        cid_nbuckets=64, max_pools=8)
    fp.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fp)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=24, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    dhcp = DHCPServer(SERVER_MAC, SERVER_IP, pools, fastpath_tables=fp,
                      nat_hook=lambda ip, now: nat.allocate_nat(ip, now),
                      clock=clock or FakeClock())
    return fp, nat, dhcp, pools


class TestHostMirrorRoundTrip:
    def test_manager_roundtrip_without_engine(self):
        fp, nat, dhcp, pools = _mk_stack()
        mac = bytes.fromhex("02c0ffee0001")
        sub_ip = ip_to_u32("10.0.0.10")
        fp.add_subscriber(mac, 1, sub_ip, T0 + 3600)
        fp.add_vlan_subscriber(100, 200, 1, sub_ip, T0 + 3600)
        fp.add_circuit_id_subscriber(b"olt1/1/1", 1, sub_ip, T0 + 3600)
        nat.allocate_nat(sub_ip, T0)
        nat.handle_new_flow(sub_ip, ip_to_u32("8.8.8.8"), 5555, 443,
                            int(PROTO_TCP), 100, T0)
        mk = mac_to_u64(mac)
        dhcp.leases[mk] = Lease(mac=mac, ip=sub_ip, pool_id=1,
                                expiry=T0 + 3600, circuit_id=b"olt1/1/1",
                                session_id="bng-1-000001", qos_policy="gold")
        dhcp.leases_by_cid[b"olt1/1/1"] = mk
        dhcp._session_seq = 9
        pppoe = PPPoEFastPathTables()

        class Sess:
            session_id, client_mac, assigned_ip = 7, b"\x02" * 6, sub_ip

        pppoe.session_up(Sess())

        ck = decode_checkpoint(encode_checkpoint(build_checkpoint(
            3, float(T0), fastpath=fp, nat=nat, pppoe=pppoe, dhcp=dhcp,
            node_id="bng0")))

        fp2, nat2, dhcp2, pools2 = _mk_stack()
        pppoe2 = PPPoEFastPathTables()
        rows = restore_checkpoint(ck, fastpath=fp2, nat=nat2, pppoe=pppoe2,
                                  dhcp=dhcp2)
        assert rows["fastpath.sub"] == 1 and rows["fastpath.vlan"] == 1
        assert rows["nat.sessions"] == 1 and rows["nat.blocks"] == 1
        assert rows["pppoe.by_sid"] == 1
        assert rows["dhcp.leases"] == 1
        for t in ("sub", "vlan", "cid"):
            assert np.array_equal(getattr(fp2, t).keys, getattr(fp, t).keys)
            assert np.array_equal(getattr(fp2, t).vals, getattr(fp, t).vals)
            assert np.array_equal(getattr(fp2, t).used, getattr(fp, t).used)
        assert np.array_equal(fp2.pools, fp.pools)
        assert np.array_equal(fp2.server, fp.server)
        assert nat2.blocks == nat.blocks
        assert nat2.eim == nat.eim
        assert nat2._ext_ports == nat._ext_ports
        assert nat2._next_block == nat._next_block
        assert nat2._sub_id_seq == nat._sub_id_seq
        lease = dhcp2.leases[mk]
        assert lease.ip == sub_ip and lease.qos_policy == "gold"
        assert dhcp2.leases_by_cid[b"olt1/1/1"] == mk
        assert dhcp2._session_seq == 9
        # pool occupancy restored: the lease's IP cannot be re-assigned
        assert pools2.pools[1].used == 1
        # a fresh allocation on the RESTORED NAT can never reuse the
        # restored subscriber's port block
        blk2 = nat2.allocate_nat(ip_to_u32("10.0.0.11"), T0)
        assert blk2["port_start"] != nat.blocks[sub_ip]["port_start"]

    def test_geometry_mismatch_rejected_before_mutation(self):
        fp, nat, dhcp, _ = _mk_stack()
        fp.add_subscriber(b"\x02" * 6, 1, ip_to_u32("10.0.0.9"), T0)
        ck = build_checkpoint(1, float(T0), fastpath=fp, nat=nat)
        fp2 = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                             cid_nbuckets=64, max_pools=8)
        nat2 = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                          sessions_nbuckets=256, sub_nat_nbuckets=64)
        nat2.allocate_nat(ip_to_u32("10.0.0.50"), T0)
        before = nat2.sub_nat.vals.copy()
        with pytest.raises(CheckpointError, match="geometry"):
            restore_checkpoint(ck, fastpath=fp2, nat=nat2)
        # the reject happened BEFORE any mirror write: nat2 untouched
        assert np.array_equal(nat2.sub_nat.vals, before)
        assert nat2.blocks  # allocator bookkeeping intact

    def test_missing_component_rejected(self):
        fp, nat, dhcp, _ = _mk_stack()
        ck = build_checkpoint(1, float(T0), fastpath=fp, nat=nat, dhcp=dhcp)
        fp2, nat2, _, _ = _mk_stack()
        with pytest.raises(CheckpointError, match="dhcp"):
            restore_checkpoint(ck, fastpath=fp2, nat=nat2)

    def test_scaling_state_rides_payload_not_header(self):
        """The lease book / NAT bookkeeping / HA store are per-row state:
        they must live in the CRC-covered payload blobs, leaving the
        header size independent of the subscriber count."""
        fp, nat, dhcp, _ = _mk_stack()
        for i in range(50):
            mac = (0x02AA000000 << 8 | i).to_bytes(6, "big")
            dhcp.leases[mac_to_u64(mac)] = Lease(
                mac=mac, ip=ip_to_u32("10.0.0.1") + i, pool_id=1,
                expiry=T0 + 3600, session_id=f"bng-{i}")
        ck = build_checkpoint(1, float(T0), fastpath=fp, nat=nat, dhcp=dhcp)
        assert ck.meta["components"]["dhcp"] == {"__payload_json__": True}
        assert "dhcp/__payload_json__" in ck.arrays
        data = encode_checkpoint(ck)
        hlen = struct.unpack_from("<II", data, len(MAGIC))[0]
        assert hlen < 8192  # geometry only — no per-lease rows
        # a bit flip INSIDE the relocated lease blob is payload-CRC'd
        blob_bytes = bytes(np.asarray(ck.arrays["dhcp/__payload_json__"]))
        off = data.rindex(blob_bytes)
        raw = bytearray(data)
        raw[off + 10] ^= 0xFF
        with pytest.raises(CheckpointError, match="crc32"):
            decode_checkpoint(bytes(raw))

    def test_corrupt_ha_session_rejected_before_mutation(self):
        """A session dict missing its required field must reject in the
        verify phase, before any table mirror was touched."""
        fp, nat, dhcp, _ = _mk_stack()
        fp.add_subscriber(b"\x02" * 6, 1, ip_to_u32("10.0.0.9"), T0)
        active = ActiveSyncer(InMemorySessionStore())
        active.push_change(SessionState(session_id="s1", ip=1))
        ck = build_checkpoint(1, float(T0), fastpath=fp, ha=active)
        blob = json.loads(bytes(np.asarray(ck.arrays["ha/__payload_json__"])))
        del blob["sessions"][0]["session_id"]  # required field gone
        ck.arrays["ha/__payload_json__"] = np.frombuffer(
            json.dumps(blob).encode(), dtype=np.uint8).copy()

        fp2, _, _, _ = _mk_stack()
        ha2 = ActiveSyncer(InMemorySessionStore())
        with pytest.raises(CheckpointError, match="ha"):
            restore_checkpoint(ck, fastpath=fp2, ha=ha2)
        assert fp2.sub.count == 0  # untouched
        assert len(ha2.store) == 0

    def test_missing_pppoe_server_mac_rejected(self):
        pppoe = PPPoEFastPathTables()
        ck = build_checkpoint(1, float(T0), pppoe=pppoe)
        del ck.arrays["pppoe/server_mac"]
        with pytest.raises(CheckpointError, match="server_mac"):
            restore_checkpoint(ck, pppoe=PPPoEFastPathTables())

    def test_corrupt_nat_meta_rejected_before_mutation(self):
        """A CRC-valid checkpoint whose NAT bookkeeping fails to parse
        must reject in the verify phase — never after the fastpath
        mirrors were already overwritten."""
        fp, nat, dhcp, _ = _mk_stack()
        sub_ip = ip_to_u32("10.0.0.10")
        fp.add_subscriber(b"\x02" * 6, 1, sub_ip, T0)
        nat.allocate_nat(sub_ip, T0)
        ck = build_checkpoint(1, float(T0), fastpath=fp, nat=nat)
        blob = json.loads(bytes(np.asarray(ck.arrays["nat/__payload_json__"])))
        del blob["eim"]  # version-skew-shaped damage, still valid JSON
        ck.arrays["nat/__payload_json__"] = np.frombuffer(
            json.dumps(blob).encode(), dtype=np.uint8).copy()

        fp2, nat2, _, _ = _mk_stack()
        before = fp2.sub.keys.copy()
        with pytest.raises(CheckpointError, match="nat"):
            restore_checkpoint(ck, fastpath=fp2, nat=nat2)
        assert np.array_equal(fp2.sub.keys, before)  # untouched
        assert fp2.sub.count == 0


class TestHACheckpoint:
    def test_standby_bootstraps_then_replays(self):
        active = ActiveSyncer(InMemorySessionStore())
        for i in range(5):
            active.push_change(SessionState(session_id=f"s{i}",
                                            ip=0x0A000000 + i))
        ck = decode_checkpoint(encode_checkpoint(
            build_checkpoint(1, 0.0, ha=active)))

        store = InMemorySessionStore()
        standby = StandbySyncer(store, transport=lambda: active)
        rows = restore_checkpoint(ck, ha=standby)
        assert rows["ha.sessions"] == 5
        assert standby.last_seq == 5
        # changes since the checkpoint arrive via REPLAY, not full sync
        active.push_change(SessionState(session_id="s9", ip=0x0A000063))
        active.push_change(None, session_id="s0")
        standby.tick(0.0)
        assert standby.connected
        assert standby.stats["full_syncs"] == 0
        assert standby.stats["deltas"] == 2
        assert store.get("s9") is not None and store.get("s0") is None

    def test_stale_checkpoint_falls_back_to_full_sync(self):
        active = ActiveSyncer(InMemorySessionStore(), replay_buffer=4)
        active.push_change(SessionState(session_id="s1", ip=1))
        ck = build_checkpoint(1, 0.0, ha=active)  # seq=1
        for i in range(2, 12):  # wrap the replay buffer past seq 1
            active.push_change(SessionState(session_id=f"s{i}", ip=i))
        standby = StandbySyncer(InMemorySessionStore(),
                                transport=lambda: active)
        restore_checkpoint(ck, ha=standby)
        standby.tick(0.0)
        assert standby.stats["full_syncs"] == 1  # replay gap -> resync
        assert len(standby.store) == 11

    def test_restarted_active_resumes_seq(self):
        active = ActiveSyncer(InMemorySessionStore())
        for i in range(3):
            active.push_change(SessionState(session_id=f"s{i}", ip=i))
        ck = build_checkpoint(1, 0.0, ha=active)
        active2 = ActiveSyncer(InMemorySessionStore())
        restore_checkpoint(ck, ha=active2)
        assert active2._seq == 3
        assert len(active2.store) == 3
        # a standby exactly at the checkpoint seq needs no resync
        assert active2.replay_since(3) == []


class TestPeriodicCheckpointer:
    def _fp_snapshot_fn(self):
        fp, nat, dhcp, _ = _mk_stack()
        return lambda seq, now: build_checkpoint(seq, now, fastpath=fp)

    def test_cadence_and_retention(self, tmp_path):
        clock = FakeClock()
        ckptr = PeriodicCheckpointer(CheckpointStore(tmp_path),
                                     self._fp_snapshot_fn(), interval_s=10.0,
                                     keep=2, clock=clock)
        assert ckptr.tick(clock()) is not None  # first tick saves
        assert ckptr.tick(clock()) is None  # not due again yet
        clock.advance(10.1)
        assert ckptr.tick(clock()) is not None
        for _ in range(4):
            clock.advance(10.1)
            ckptr.tick(clock())
        assert ckptr.stats["saves"] == 6
        assert len(ckptr.store.list()) == 2  # retention applied
        assert ckptr.store.next_seq() == 7  # seq stays monotonic

    def test_background_failure_counts_and_never_raises(self, tmp_path):
        clock = FakeClock()

        def boom(seq, now):
            raise OSError("disk full")

        ckptr = PeriodicCheckpointer(CheckpointStore(tmp_path), boom,
                                     interval_s=1.0, clock=clock)
        for _ in range(3):
            clock.advance(1.1)
            assert ckptr.tick(clock()) is None  # swallowed, counted
        assert ckptr.stats["failures"] == 3
        assert "disk full" in ckptr.stats["last_error"]
        # the manual path (CLI / SIGTERM) propagates instead
        with pytest.raises(OSError):
            ckptr.save_now(reason="cli")
        # staleness metric: never-succeeded reads as a GROWING age from
        # checkpointer start, not a perpetually-fresh 0
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        m.collect_checkpoint(ckptr, now=clock())
        assert m.ckpt_last_success_age.value() > 3.0


class TestVectorizedExpiry:
    def test_per_protocol_timeouts(self):
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        now = T0
        specs = [  # (src_ip, proto, state, idle_s, should_expire)
            (1, PROTO_UDP, 0, UDP_TIMEOUT_S + 1, True),
            (2, PROTO_UDP, 0, UDP_TIMEOUT_S - 1, False),
            (3, PROTO_TCP, 1, TCP_EST_TIMEOUT_S + 1, True),
            (4, PROTO_TCP, 1, TCP_EST_TIMEOUT_S - 1, False),
            (5, PROTO_TCP, 0, TCP_TRANSIENT_TIMEOUT_S + 1, True),
            (6, PROTO_ICMP, 0, ICMP_TIMEOUT_S + 1, True),
            (7, PROTO_ICMP, 0, ICMP_TIMEOUT_S - 1, False),
            # CLOSING caps the established timeout at transient
            (8, PROTO_TCP, NAT_STATE_CLOSING,
             TCP_TRANSIENT_TIMEOUT_S + 1, True),
        ]
        for ip, proto, state, idle, _ in specs:
            nat.allocate_nat(ip, now - idle)
            got = nat.handle_new_flow(ip, ip_to_u32("8.8.8.8"), 40000, 443,
                                      int(proto), 100, now - idle)
            assert got is not None
            slot = nat.sessions._find_slot(np.asarray(
                nat._key(ip, ip_to_u32("8.8.8.8"),
                         40000, 0 if proto == PROTO_ICMP else 443,
                         int(proto)), dtype=np.uint32))
            nat.sessions.vals[slot, SV_STATE] = state
            nat.sessions.vals[slot, SV_LAST_SEEN] = now - idle
            assert int(nat.sessions.vals[slot, SV_PROTO]) == int(proto)
        expected = sum(1 for *_x, e in specs if e)
        assert nat.expire_sessions(now) == expected
        assert nat.sessions.count == len(specs) - expected
        # survivors intact, expired gone (reverse rows too)
        assert nat.sessions.count == nat.reverse.count
        assert nat.expire_sessions(now) == 0  # idempotent

    def test_empty_sweep(self):
        nat = NATManager(public_ips=[1], sessions_nbuckets=256,
                         sub_nat_nbuckets=64)
        assert nat.expire_sessions(T0) == 0


class TestFoldDeviceAuthoritative:
    def test_fold_skips_not_yet_uploaded_rows(self):
        """A host NAT session the bounded drain has not scattered yet
        reads back zeros from HBM — the pre-checkpoint fold must keep
        the NEWER host row, not clobber it with the stale device slot.
        (No jit dispatch: engine construction uploads, then we mutate
        the host side only — fast-tier safe.)"""
        from bng_tpu.runtime.engine import Engine

        clock = FakeClock()
        fp, nat, dhcp, _ = _mk_stack(clock, sub_nbuckets=128)
        sub_ip = ip_to_u32("10.0.0.77")
        nat.allocate_nat(sub_ip, T0)
        # uploaded session: on device since engine construction
        nat.handle_new_flow(sub_ip, ip_to_u32("1.1.1.1"), 1111, 80,
                            int(PROTO_UDP), 64, T0)
        engine = Engine(fp, nat, batch_size=8, clock=clock)
        assert nat.sessions.dirty_count() == 0  # init upload drained all
        # NEW session after the upload: dirty, device slot still zeros
        nat.handle_new_flow(sub_ip, ip_to_u32("2.2.2.2"), 2222, 80,
                            int(PROTO_UDP), 64, T0 + 5)
        key = np.asarray(nat._key(sub_ip, ip_to_u32("2.2.2.2"),
                                  2222, 80, int(PROTO_UDP)),
                         dtype=np.uint32)
        slot = nat.sessions._find_slot(key)
        row_before = nat.sessions.vals[slot].copy()
        assert row_before.any()
        engine.fold_device_authoritative()
        # pending host row survived; the uploaded row got device values
        assert np.array_equal(nat.sessions.vals[slot], row_before)
        up_key = np.asarray(nat._key(sub_ip, ip_to_u32("1.1.1.1"),
                                     1111, 80, int(PROTO_UDP)),
                            dtype=np.uint32)
        up_slot = nat.sessions._find_slot(up_key)
        dev = engine.fetch_session_vals()
        assert np.array_equal(nat.sessions.vals[up_slot], dev[up_slot])


# ---------------------------------------------------------------------------
# slow tier: full engine round trip (compile-heavy -> make verify-slow)
# ---------------------------------------------------------------------------

def _mk_engine_stack(clock, sub_nbuckets=256):
    from bng_tpu.runtime.engine import (AntispoofTables, Engine, QoSTables)

    fp, nat, dhcp, pools = _mk_stack(clock, sub_nbuckets=sub_nbuckets)
    qos = QoSTables(nbuckets=256)
    spoof = AntispoofTables(nbuckets=256)
    engine = Engine(fp, nat, qos, spoof, batch_size=8,
                    slow_path=dhcp.handle_frame, clock=clock)
    return engine, dhcp, nat, fp


def _client_frame(mac, msg_type, **kw):
    from bng_tpu.control import dhcp_codec, packets

    pkt = dhcp_codec.build_request(mac, msg_type, **kw)
    pkt.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                        bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              pkt.encode().ljust(320, b"\x00"))


@pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
class TestEngineRoundTrip:
    def test_save_restore_fastpath_parity(self, tmp_path):
        from bng_tpu.control import dhcp_codec, packets

        clock = FakeClock()
        engine, dhcp, nat, fp = _mk_engine_stack(clock)
        mac = bytes.fromhex("02c0ffee0042")
        sub_remote = ip_to_u32("93.184.216.34")

        # live traffic: full DORA (slow path populates the device cache)
        r = engine.process([_client_frame(mac, dhcp_codec.DISCOVER)])
        offer = dhcp_codec.decode(packets.decode(r["slow"][0][1]).payload)
        ip = offer.yiaddr
        engine.process([_client_frame(mac, dhcp_codec.REQUEST,
                                      requested_ip=ip, server_id=SERVER_IP)])
        # NAT conntrack-hybrid: packet 1 punts, packet 2 device-SNATs
        f = packets.udp_packet(mac, SERVER_MAC, ip, sub_remote, 40000, 443,
                               b"data")
        engine.process([f])
        r = engine.process([f])
        nat_port = packets.decode(r["fwd"][0][1]).src_port

        # snapshot at the quiesce barrier, through the versioned store
        store = CheckpointStore(tmp_path)
        ckptr = PeriodicCheckpointer(
            store, lambda seq, now: build_checkpoint(
                seq, now, engine=engine, dhcp=dhcp), clock=clock)
        ckptr.save_now(reason="test")

        # ---- fresh process: restore, expect ZERO slow-path DHCP ----
        clock2 = FakeClock(clock())
        engine2, dhcp2, nat2, fp2 = _mk_engine_stack(clock2)
        snap, _ = store.load_latest()
        rows = restore_checkpoint(snap, engine=engine2, dhcp=dhcp2)
        assert rows["fastpath.sub"] == 1
        assert rows["nat.sessions"] == 1
        assert rows["dhcp.leases"] == 1

        # table-content equality across the restart
        for t in ("sub", "vlan", "cid"):
            assert np.array_equal(getattr(fp2, t).keys,
                                  getattr(fp, t).keys)
            assert np.array_equal(getattr(fp2, t).vals,
                                  getattr(fp, t).vals)
        assert np.array_equal(nat2.sessions.keys, nat.sessions.keys)
        assert nat2.blocks == nat.blocks and nat2.eim == nat.eim

        # DISCOVER answered ON DEVICE — no DHCP slow-path exchange
        r = engine2.process([_client_frame(mac, dhcp_codec.DISCOVER)])
        assert len(r["tx"]) == 1 and r["slow"] == []
        dev_offer = dhcp_codec.decode(packets.decode(r["tx"][0][1]).payload)
        assert dev_offer.msg_type == dhcp_codec.OFFER
        assert dev_offer.yiaddr == ip
        assert dhcp2.stats.discover == 0 and dhcp2.stats.offer == 0

        # restored NAT session device-SNATs with the SAME mapping
        r = engine2.process([f])
        assert len(r["fwd"]) == 1
        d = packets.decode(r["fwd"][0][1])
        assert d.src_ip == ip_to_u32("203.0.113.1")
        assert d.src_port == nat_port

        # renewal REQUEST also on-device
        r = engine2.process([_client_frame(mac, dhcp_codec.REQUEST,
                                           requested_ip=ip,
                                           server_id=SERVER_IP)])
        assert len(r["tx"]) == 1
        assert dhcp2.stats.request == 0

    def test_scheduler_quiesce_barrier(self):
        from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler

        clock = FakeClock()
        # distinct DHCP-table geometry: the express dispatch below
        # compiles a B=8 shape into the geometry-keyed shared jit cache,
        # and test_hlo_structure's compile-shape-budget test counts the
        # shapes of the DEFAULT-geometry callable — don't pollute it
        engine, dhcp, nat, fp = _mk_engine_stack(clock, sub_nbuckets=128)
        sched = TieredScheduler(engine, SchedulerConfig(express_batch=8),
                                clock=clock)
        from bng_tpu.control import dhcp_codec

        mac = bytes.fromhex("02c0ffee0099")
        # leave frames QUEUED (below batch, before the deadline): quiesce
        # must ship and retire them, not strand them
        for i in range(3):
            sched.submit(_client_frame(mac, dhcp_codec.DISCOVER),
                         from_access=True)
        retired = sched.quiesce()
        assert retired == 3
        assert len(sched.express) == 0 and len(sched.bulk) == 0
        assert len(sched._express_ring) == 0 and len(sched._bulk_ring) == 0
        # a snapshot right at the barrier sees a consistent cut
        ck = build_checkpoint(1, clock(), engine=engine, scheduler=sched,
                              dhcp=dhcp)
        assert "fastpath" in ck.meta["components"]
