"""Control-plane unit tests: state store, subscriber lifecycle, nexus."""

import pytest

from bng_tpu.control.nexus import (
    ErrNoAllocation,
    HTTPAllocator,
    IPPoolEntity,
    MemoryStore,
    NexusClient,
    SubscriberEntity,
    TypedStore,
    VLANAllocator,
)
from bng_tpu.control.state import (
    LeaseRecord,
    PoolRecord,
    SessionRecord,
    Store,
    Subscriber,
)
from bng_tpu.control.subscriber import SessionKind, SessionState, SubscriberManager


class TestStateStore:
    def test_subscriber_indexes(self):
        st = Store()
        st.put_subscriber(Subscriber(id="s1", mac="02:AA:BB:CC:DD:01",
                                     circuit_id="olt1/1/1", nte_id="nte-1"))
        assert st.subscriber_by_mac("02:aa:bb:cc:dd:01").id == "s1"
        assert st.subscriber_by_circuit_id("olt1/1/1").id == "s1"
        assert [s.id for s in st.subscribers_by_nte("nte-1")] == ["s1"]
        assert st.delete_subscriber("s1")
        assert st.subscriber_by_mac("02:aa:bb:cc:dd:01") is None

    def test_pool_matching_specificity(self):
        st = Store()
        st.put_pool(PoolRecord(id="any", cidr="10.0.0.0/24"))
        st.put_pool(PoolRecord(id="biz", cidr="10.1.0.0/24", client_class=2))
        st.put_pool(PoolRecord(id="biz-ispA", cidr="10.2.0.0/24", client_class=2, isp_id="A"))
        biz_sub = Subscriber(id="b", client_class=2, isp_id="A")
        assert st.find_pool_for_subscriber(biz_sub).id == "biz-ispA"
        res_sub = Subscriber(id="r", client_class=0)
        assert st.find_pool_for_subscriber(res_sub).id == "any"

    def test_lease_expiry_sweep(self):
        t = [1000.0]
        st = Store(clock=lambda: t[0])
        st.put_lease(LeaseRecord(ip="10.0.0.5", subscriber_id="s1",
                                 mac="02:aa", expires_at=1100))
        assert st.cleanup_expired_leases() == 0
        t[0] = 1200
        assert st.cleanup_expired_leases() == 1
        assert st.lease_by_mac("02:aa") is None

    def test_session_idle_sweep(self):
        t = [1000.0]
        st = Store(clock=lambda: t[0])
        st.put_session(SessionRecord(id="x", subscriber_id="s1", last_seen=1000))
        t[0] = 5000
        assert st.cleanup_idle_sessions(idle_s=3600) == 1


class TestSubscriberManager:
    def test_full_lifecycle(self):
        events = []
        alloc = type("A", (), {
            "allocate": lambda self, sid: "10.0.0.9",
            "release": lambda self, sid: True,
        })()
        m = SubscriberManager(
            authenticator=lambda s: {"subscriber_id": "sub-1"},
            allocator=alloc,
            event_sink=lambda e: events.append(e.event),
        )
        s = m.create_session(SessionKind.IPOE, mac="02:AA:BB:00:00:01")
        assert m.authenticate(s.id)
        assert m.assign_address(s.id) == "10.0.0.9"
        m.activate(s.id)
        assert m.sessions[s.id].state == SessionState.ACTIVE
        assert m.by_mac("02:aa:bb:00:00:01").id == s.id
        assert m.terminate(s.id)
        assert events == ["created", "authenticated", "address_assigned",
                          "active", "terminated"]

    def test_auth_failure_goes_walled(self):
        garden = []
        wg = type("W", (), {
            "add": lambda self, s: garden.append(s.id),
            "remove": lambda self, s: garden.remove(s.id),
        })()
        m = SubscriberManager(authenticator=lambda s: None, walled_garden=wg)
        s = m.create_session(SessionKind.WIFI, mac="02:BB:00:00:00:01")
        assert not m.authenticate(s.id)
        assert m.sessions[s.id].state == SessionState.WALLED_GARDEN
        assert garden == [s.id]
        m.activate(s.id)  # portal auth succeeded later
        assert garden == []

    def test_idle_cleanup(self):
        t = [1000.0]
        m = SubscriberManager(idle_timeout_s=100, clock=lambda: t[0])
        s = m.create_session(SessionKind.IPOE, mac="02:CC:00:00:00:01")
        t[0] = 1050
        assert m.cleanup_idle() == 0
        t[0] = 1200
        assert m.cleanup_idle() == 1
        assert s.id not in m.sessions


class TestNexus:
    def test_typed_store_and_watch(self):
        store = MemoryStore()
        subs = TypedStore(store, "subscribers", SubscriberEntity)
        changes = []
        subs.watch(lambda id_, obj: changes.append((id_, obj)))
        subs.put("s1", SubscriberEntity(id="s1", mac="02:aa"))
        got = subs.get("s1")
        assert got.mac == "02:aa"
        subs.delete("s1")
        assert changes[0][0] == "s1" and changes[0][1].mac == "02:aa"
        assert changes[1] == ("s1", None)

    def test_hashring_allocation_deterministic(self):
        c1 = NexusClient(MemoryStore())
        c1.pools.put("p1", IPPoolEntity(id="p1", cidr="10.10.0.0/24"))
        ip_a = c1.allocate_ip("sub-A", "p1")
        assert ip_a and ip_a.startswith("10.10.0.")
        # idempotent for the same subscriber
        assert c1.allocate_ip("sub-A", "p1") == ip_a
        # a different client over the SAME store agrees without coordination
        c2 = NexusClient(c1.store, node_id="bng1")
        c2.pools = c1.pools
        assert c2.allocate_ip("sub-A", "p1") == ip_a
        assert c1.release_ip("sub-A", "p1")
        assert c1.store.get("allocations/p1/by-ip/" + ip_a) is None

    def test_subscriber_lookup_by_mac(self):
        c = NexusClient()
        c.subscribers.put("s1", SubscriberEntity(id="s1", mac="02:AA:BB:CC:DD:EE"))
        assert c.get_subscriber_by_mac("02:aa:bb:cc:dd:ee").id == "s1"
        assert c.get_subscriber_by_mac("02:00:00:00:00:00") is None


class FakeNexusHTTP:
    """In-memory Nexus REST endpoint (httpmock role)."""

    def __init__(self):
        self.allocs = {}
        self.next = 10
        self.healthy = True

    def __call__(self, method, path, body):
        if not self.healthy:
            return 503, {}
        if path == "/health":
            return 200, {}
        if method == "POST" and path == "/api/v1/allocate":
            sid = body["subscriber_id"]
            if sid not in self.allocs:
                self.allocs[sid] = f"100.64.0.{self.next}"
                self.next += 1
            return 200, {"ip": self.allocs[sid]}
        if method == "GET" and path.startswith("/api/v1/allocations/"):
            sid = path.rsplit("/", 1)[1]
            return (200, {"ip": self.allocs[sid]}) if sid in self.allocs else (404, {})
        if method == "DELETE" and path.startswith("/api/v1/allocations/"):
            sid = path.rsplit("/", 1)[1]
            return (204, {}) if self.allocs.pop(sid, None) else (404, {})
        if path == "/api/v1/pools":
            return 200, {"pools": [{"id": "p1", "used": len(self.allocs)}]}
        return 404, {}


class TestHTTPAllocator:
    def test_allocate_lookup_release(self):
        server = FakeNexusHTTP()
        a = HTTPAllocator("http://nexus", server)
        ip = a.allocate("sub-1")
        assert ip == "100.64.0.10"
        assert a.lookup("sub-1") == ip
        assert a.release("sub-1")
        assert a.lookup("sub-1") is None
        assert a.health_check()

    def test_server_error_raises(self):
        server = FakeNexusHTTP()
        server.healthy = False
        a = HTTPAllocator("http://nexus", server)
        with pytest.raises(ConnectionError):
            a.allocate("sub-1")
        assert not a.health_check()


class TestVLANAllocator:
    def test_allocate_unique_pairs(self):
        v = VLANAllocator(s_tag_range=(100, 101), c_tag_range=(1, 3))
        pairs = [v.allocate(f"s{i}") for i in range(6)]
        assert len(set(pairs)) == 6
        assert v.allocate("overflow") is None
        assert v.allocate("s0") == pairs[0]  # sticky
        assert v.release("s0")
        assert v.allocate("s-new") is not None


class TestStateStoreDepth:
    """Round-4 store depth (store.go:100-1024 parity): list/update CRUD,
    pool names, lease renew, session activity + by-MAC/IP indexes, NAT
    by-public interval lookup, stats, background sweeps."""

    def _store(self):
        from bng_tpu.control import state as st

        clk = {"t": 1000.0}
        s = st.Store(clock=lambda: clk["t"])
        return st, s, clk

    def test_update_subscriber_requires_existing(self):
        st, s, _ = self._store()
        with pytest.raises(KeyError):
            s.update_subscriber(st.Subscriber(id="ghost"))
        s.put_subscriber(st.Subscriber(id="s1", mac="02:00:00:00:00:01"))
        s.update_subscriber(st.Subscriber(id="s1", mac="02:00:00:00:00:02"))
        assert s.subscriber_by_mac("02:00:00:00:00:02").id == "s1"
        assert s.subscriber_by_mac("02:00:00:00:00:01") is None
        assert [x.id for x in s.list_subscribers()] == ["s1"]

    def test_pool_name_index_and_delete(self):
        st, s, _ = self._store()
        s.put_pool(st.PoolRecord(id="p1", cidr="10.0.0.0/24", name="resi"))
        assert s.pool_by_name("resi").id == "p1"
        s.put_pool(st.PoolRecord(id="p1", cidr="10.0.0.0/24", name="biz"))
        assert s.pool_by_name("resi") is None
        assert s.pool_by_name("biz").id == "p1"
        assert s.delete_pool("p1") and not s.delete_pool("p1")
        assert s.pool_by_name("biz") is None

    def test_lease_renew_extends_from_now(self):
        st, s, clk = self._store()
        s.put_lease(st.LeaseRecord(ip="10.0.0.5", subscriber_id="s1",
                                   mac="02:00:00:00:00:05",
                                   expires_at=1100.0))
        clk["t"] = 1090.0
        assert s.renew_lease("10.0.0.5", 3600)
        assert s.lease_by_ip("10.0.0.5").expires_at == 1090.0 + 3600
        assert not s.renew_lease("10.9.9.9", 3600)

    def test_session_indexes_and_activity(self):
        st, s, clk = self._store()
        s.put_session(st.SessionRecord(id="sess1", subscriber_id="s1",
                                       ip="10.0.0.7",
                                       mac="02:00:00:00:00:07",
                                       last_seen=1000.0))
        assert s.session_by_mac("02:00:00:00:00:07").id == "sess1"
        assert s.session_by_ip("10.0.0.7").id == "sess1"
        clk["t"] = 2000.0
        assert s.update_session_activity("sess1", bytes_in=100, bytes_out=50)
        sess = s.sessions["sess1"]
        assert (sess.bytes_in, sess.bytes_out, sess.last_seen) == (100, 50, 2000.0)
        # activity keeps the idle reaper away
        assert s.cleanup_idle_sessions(idle_s=3600, now=2100.0) == 0
        assert s.cleanup_idle_sessions(idle_s=50, now=9000.0) == 1
        assert s.session_by_ip("10.0.0.7") is None  # indexes cleaned

    def test_nat_by_public_interval_lookup(self):
        st, s, _ = self._store()
        s.put_nat_binding(st.NATBinding(private_ip="10.0.0.8",
                                        public_ip="203.0.113.1",
                                        port_start=1024, port_end=2047))
        s.put_nat_binding(st.NATBinding(private_ip="10.0.0.9",
                                        public_ip="203.0.113.1",
                                        port_start=2048, port_end=3071))
        assert s.nat_binding_by_public("203.0.113.1", 1500).private_ip == "10.0.0.8"
        assert s.nat_binding_by_public("203.0.113.1", 2048).private_ip == "10.0.0.9"
        assert s.nat_binding_by_public("203.0.113.1", 5000) is None
        assert s.nat_binding_by_public("203.0.113.9", 1500) is None
        assert s.delete_nat_binding("10.0.0.8")
        assert s.nat_binding_by_public("203.0.113.1", 1500) is None

    def test_stats_and_background_sweep(self):
        st, s, clk = self._store()
        s.lease_sweep_interval = 0.05
        s.put_lease(st.LeaseRecord(ip="10.0.0.5", subscriber_id="s1",
                                   mac="02:00:00:00:00:05",
                                   expires_at=1100.0))
        clk["t"] = 5000.0
        s.start()
        import time as _time

        for _ in range(40):
            if not s.leases:
                break
            _time.sleep(0.05)
        s.stop()
        assert s.leases == {}
        assert s.stats()["leases_expired"] == 1

    def test_sweep_races_foreground_crud_safely(self):
        """The background sweeper must survive concurrent CRUD (review
        r4: the lock-free store killed the sweep thread with
        dict-changed-during-iteration)."""
        import threading as th
        import time as _time

        st, s, clk = self._store()
        s.lease_sweep_interval = 0.001
        clk["t"] = 10_000.0
        stop = th.Event()
        errors = []

        def churn():
            i = 0
            try:
                while not stop.is_set():
                    ip = f"10.1.{i % 250}.{(i // 250) % 250}"
                    s.put_lease(st.LeaseRecord(
                        ip=ip, subscriber_id="s", mac=f"02:00:00:00:{i % 99:02d}:01",
                        expires_at=9_000.0))  # always already expired
                    s.put_session(st.SessionRecord(
                        id=f"x{i % 500}", subscriber_id="s", ip=ip,
                        last_seen=0.0))
                    s.delete_session(f"x{(i + 250) % 500}")
                    i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        s.start()
        workers = [th.Thread(target=churn) for _ in range(3)]
        for w in workers:
            w.start()
        _time.sleep(0.5)
        stop.set()
        for w in workers:
            w.join(timeout=2)
        # sweeper thread must still be alive (not killed by a race)
        assert s._thread.is_alive()
        s.stop()
        assert not errors, errors[:1]
        assert s.stats()["leases_expired"] > 0

    def test_reassigned_mac_keeps_new_owner_index(self):
        """Deleting the OLD subscriber must not clobber the index entry a
        reassigned MAC/circuit-id now points at (review r4)."""
        st, s, _ = self._store()
        s.put_subscriber(st.Subscriber(id="s1", mac="02:00:00:00:00:0a",
                                       circuit_id="olt1/1"))
        s.put_subscriber(st.Subscriber(id="s2", mac="02:00:00:00:00:0a",
                                       circuit_id="olt1/1"))
        assert s.delete_subscriber("s1")
        assert s.subscriber_by_mac("02:00:00:00:00:0a").id == "s2"
        assert s.subscriber_by_circuit_id("olt1/1").id == "s2"

    def test_double_start_keeps_one_sweeper(self):
        import threading as th

        st, s, _ = self._store()
        s.lease_sweep_interval = 10.0
        s.start()
        t1 = s._thread
        s.start()
        assert s._thread is t1  # no orphaned second sweeper
        before = sum(1 for t in th.enumerate()
                     if t.name == "bng-state-sweep")
        assert before == 1
        s.stop()
