"""SLO engine tests (telemetry/slo.py): spec validation, one-shot
evaluation, the storm-budget re-home (verdicts byte-identical to the
PR-8 originals), the live burn-rate monitor firing the slo_breach
flight dump, and the sharded-path ShardTelemetry counters + histogram
merge laws. `make verify-perf` runs the `perf` marker."""

from __future__ import annotations

import json

import numpy as np
import pytest

from bng_tpu.telemetry import FlightRecorder, RecorderConfig
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry import slo

pytestmark = pytest.mark.perf


# ---------------------------------------------------------------------------
# spec + registry
# ---------------------------------------------------------------------------

class TestSpec:
    def test_unknown_stage_rejected(self):
        with pytest.raises(ValueError, match="unknown stage"):
            slo.SLOSpec("warp_drive", 100.0)
        with pytest.raises(ValueError, match="unknown stage"):
            slo.BudgetLine("warp_drive", 100.0)

    def test_nonpositive_limits_rejected(self):
        with pytest.raises(ValueError):
            slo.SLOSpec("dispatch", 0.0)
        with pytest.raises(ValueError):
            slo.SLOSpec("dispatch", 10.0, per=0.0)

    def test_default_registry_covers_every_stage(self):
        # Dapper's lesson machine-checked: the shipped registry budgets
        # EVERY stage of the fixed vocabulary, not just the headline
        budgeted = {s.stage for s in slo.DEFAULT_SLOS}
        assert budgeted == set(tele.STAGE_NAMES)

    def test_device_budget_is_the_paper_target(self):
        dev = [s for s in slo.DEFAULT_SLOS if s.stage == "device"]
        assert dev[0].p99_limit_us == \
            slo.HEADLINE_TARGETS["offer_device_only_p99_us"] == 50.0

    def test_parse_budgets(self):
        specs = slo.parse_budgets(["dispatch:1000", "fleet:2000:64"])
        assert specs[0].stage == "dispatch"
        assert specs[0].p99_limit_us == 1000.0 and specs[0].per == 1.0
        assert specs[1].per == 64.0
        with pytest.raises(ValueError, match="bad SLO budget"):
            slo.parse_budgets(["dispatch"])
        with pytest.raises(ValueError, match="unknown stage"):
            slo.parse_budgets(["nope:10"])


class TestEvaluate:
    def test_ok_and_breach(self):
        bd = {"dispatch": {"p99_us": 10.0}, "reply": {"p99_us": 999.0}}
        specs = (slo.SLOSpec("dispatch", 100.0), slo.SLOSpec("reply", 100.0))
        v = slo.evaluate(bd, specs)
        assert v == {"ok": False, "breaches": ["reply"]}
        v = slo.evaluate({"dispatch": {"p99_us": 10.0}},
                         (slo.SLOSpec("dispatch", 100.0),))
        assert v == {"ok": True, "breaches": []}

    def test_required_missing_is_a_coverage_hole(self):
        v = slo.evaluate({}, (slo.SLOSpec("fleet", 100.0, required=True),))
        assert v == {"ok": False, "breaches": ["fleet:missing"]}

    def test_optional_missing_skipped(self):
        v = slo.evaluate({}, (slo.SLOSpec("fleet", 100.0),))
        assert v["ok"]

    def test_per_amortization(self):
        bd = {"fleet": {"p99_us": 6400.0}}
        assert slo.evaluate(bd, (slo.SLOSpec("fleet", 200.0, per=64),))["ok"]
        assert not slo.evaluate(
            bd, (slo.SLOSpec("fleet", 50.0, per=64),))["ok"]


# ---------------------------------------------------------------------------
# the storm-budget re-home: byte-identical verdicts
# ---------------------------------------------------------------------------

class TestBudgetRehome:
    def test_storms_import_is_the_slo_objects(self):
        import bng_tpu.chaos.storms as storms

        assert storms.BudgetLine is slo.BudgetLine
        assert storms.check_budget is slo.check_budget

    def test_verdict_bytes_identical_to_pr8_semantics(self):
        """The PR-8 check_budget contract, replayed against the re-homed
        evaluator: mean-based, `per` amortization, required-missing as
        `stage:missing`, breaches sorted — and the serialized verdict
        (what lands in the bit-compared storm reports) byte-equal to the
        hand-built expectation."""
        tr = tele.Tracer()
        for _ in range(4):
            tr.hists[tele.FLEET].record(1000.0)   # mean 1000
            tr.hists[tele.ADMIT].record(10.0)     # mean 10
        lines = (
            slo.BudgetLine("admit", limit_us=50.0),            # ok
            slo.BudgetLine("fleet", limit_us=100.0, per=5.0),  # 200 > 100
            slo.BudgetLine("worker", limit_us=1.0),            # missing
            slo.BudgetLine("device", limit_us=1.0, required=False),
        )
        v = slo.check_budget(tr, lines)
        expected = {"ok": False, "breaches": ["fleet", "worker:missing"]}
        assert v == expected
        assert json.dumps(v, sort_keys=True) == \
            json.dumps(expected, sort_keys=True)

    def test_clean_budget_verdict(self):
        tr = tele.Tracer()
        tr.hists[tele.ADMIT].record(1.0)
        v = slo.check_budget(tr, (slo.BudgetLine("admit", 100.0),))
        assert v == {"ok": True, "breaches": []}

    def test_breach_fires_slo_breach_trigger(self, tmp_path):
        rec = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        with tele.armed(recorder=rec) as tr:
            tr.hists[tele.FLEET].record(1000.0)
            slo.check_budget(tr, (slo.BudgetLine("fleet", 1.0),))
        assert rec.triggers.get("slo_breach") == 1


# ---------------------------------------------------------------------------
# live burn-rate monitor
# ---------------------------------------------------------------------------

def _feed(tr, stage, us, n=64):
    for _ in range(n):
        tr.observe(stage, us)


class TestMonitor:
    def _mon(self, tmp_path, **kw):
        rec = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        tr = tele.Tracer(recorder=rec)
        mon = slo.SLOMonitor(tr, slos=(slo.SLOSpec("dispatch", 100.0),),
                             window_s=10.0, burn_windows=2, **kw)
        return rec, tr, mon

    def test_burn_rate_breach_fires_flight_dump(self, tmp_path):
        rec, tr, mon = self._mon(tmp_path)
        prev = tele.tracer()
        tele.arm(tr)
        try:
            t = 0.0
            mon.tick(t)
            _feed(tr, tele.DISPATCH, 50.0)
            t += 11
            assert mon.tick(t) == []          # healthy window
            _feed(tr, tele.DISPATCH, 500.0)
            t += 11
            assert mon.tick(t) == []          # first bad window: burning
            assert mon.snapshot()["burning"]["dispatch"] == 1
            _feed(tr, tele.DISPATCH, 500.0)
            t += 11
            assert mon.tick(t) == ["dispatch"]  # second: breach
        finally:
            tele.disarm()
            if prev is not None:
                tele.arm(prev)
        assert mon.breaches["dispatch"] == 1
        assert rec.triggers.get("slo_breach") == 1
        assert rec.dump_paths, "breach must dump the flight ring"
        body = json.loads(open(rec.dump_paths[0]).read())
        assert body["reason"] == "slo_breach"
        assert "dispatch" in body["detail"]

    def test_windowed_not_cumulative(self, tmp_path):
        """Hours of healthy history must not dilute a fresh regression:
        the windowed p99 comes from bucket-count deltas only."""
        _rec, tr, mon = self._mon(tmp_path)
        t = 0.0
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 10.0, n=10_000)  # long healthy history
        t += 11
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 500.0, n=64)     # fresh regression
        t += 11
        mon.tick(t)
        p99 = mon.snapshot()["window_p99_us"]["dispatch"]
        assert p99 > 400.0, f"window p99 {p99} diluted by history"

    def test_quiet_window_skipped_and_resets_burn(self, tmp_path):
        _rec, tr, mon = self._mon(tmp_path)
        t = 0.0
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 500.0)
        t += 11
        mon.tick(t)
        assert mon.snapshot()["burning"]["dispatch"] == 1
        # silence (below min_samples) is not a breach — and resets burn
        t += 11
        assert mon.tick(t) == []
        assert mon.snapshot()["burning"]["dispatch"] == 0

    def test_healthy_window_resets_burn(self, tmp_path):
        _rec, tr, mon = self._mon(tmp_path)
        t = 0.0
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 500.0)
        t += 11
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 10.0)
        t += 11
        assert mon.tick(t) == []
        assert mon.snapshot()["burning"]["dispatch"] == 0
        assert mon.breaches["dispatch"] == 0

    def test_snapshot_shape(self, tmp_path):
        _rec, _tr, mon = self._mon(tmp_path)
        snap = mon.snapshot()
        assert snap["budgets_us"] == {"dispatch": 100.0}
        assert snap["ok"] is True
        assert set(snap) >= {"windows", "window_s", "burn_windows",
                             "burning", "breaches", "window_p99_us"}


class TestCountsPercentile:
    def test_matches_latencyhist_geometry(self):
        from bng_tpu.telemetry.hist import LatencyHist

        rng = np.random.default_rng(3)
        vals = rng.uniform(10.0, 5000.0, size=500)
        h = LatencyHist()
        h.record_many(vals)
        got = slo._counts_percentile(h.counts, 99.0)
        ref = float(np.percentile(vals, 99))
        assert abs(got - ref) / ref < 0.15  # bucket-midpoint error bound

    def test_empty_counts(self):
        assert slo._counts_percentile(np.zeros(8, dtype=np.int64), 99) == 0.0


# ---------------------------------------------------------------------------
# sharded-path telemetry (parallel/sharded.py ShardTelemetry)
# ---------------------------------------------------------------------------

class TestShardTelemetry:
    def _rec(self, st, seed):
        rng = np.random.default_rng(seed)
        n, b = st.n, st.b
        length = rng.integers(0, 2, size=n * b).astype(np.uint32) * 100
        verdict = rng.integers(0, 4, size=n * b).astype(np.uint8)
        punt = rng.integers(0, 2, size=n * b).astype(bool)
        viol = np.zeros(n * b, dtype=bool)
        st.record_fused(length, verdict, punt, viol, 7,
                        dispatch_us=100.0 * (seed + 1),
                        wait_us=10.0 * (seed + 1))
        return length, verdict, punt

    def test_counters_from_lane_regions(self):
        from bng_tpu.parallel.sharded import ShardTelemetry

        st = ShardTelemetry(2, 4)
        length = np.array([100, 100, 0, 0, 100, 100, 100, 100],
                          dtype=np.uint32)
        verdict = np.array([2, 0, 1, 1, 3, 3, 1, 0], dtype=np.uint8)
        punt = np.array([0, 1, 0, 0, 0, 0, 0, 0], dtype=bool)
        viol = np.array([0, 0, 0, 0, 0, 0, 1, 0], dtype=bool)
        st.record_fused(length, verdict, punt, viol, 5, 100.0, 10.0)
        snap = st.snapshot()
        s0, s1 = snap["per_shard"]
        # shard 0: 2 real lanes (tx, pass); padding lanes never counted
        assert s0["frames"] == 2
        assert s0["verdicts"] == {"pass": 1, "drop": 0, "tx": 1, "fwd": 0}
        assert s0["nat_punts"] == 1
        # shard 1: fwd, fwd, drop, pass; one violation
        assert s1["frames"] == 4
        assert s1["verdicts"] == {"pass": 1, "drop": 1, "tx": 0, "fwd": 2}
        assert s1["violations"] == 1
        assert snap["psum_dhcp_hits"] == 5
        assert snap["pass_total"] == 2

    def test_dhcp_lane_counts(self):
        from bng_tpu.parallel.sharded import ShardTelemetry

        st = ShardTelemetry(2, 2)
        length = np.array([100, 100, 100, 0], dtype=np.uint32)
        is_reply = np.array([True, False, True, False])
        st.record_dhcp(length, is_reply, 2, 50.0, 5.0)
        snap = st.snapshot()
        assert snap["per_shard"][0]["dhcp_replies"] == 1
        assert snap["per_shard"][0]["verdicts"]["pass"] == 1
        assert snap["per_shard"][1]["dhcp_replies"] == 1
        # the padding lane on shard 1 is not a punt
        assert snap["per_shard"][1]["verdicts"]["pass"] == 0

    def test_merge_laws(self):
        """The merged view is plain counter addition over per-shard
        histograms — associative and commutative, the same law the
        fleet's worker-histogram merge is pinned to."""
        from bng_tpu.parallel.sharded import ShardTelemetry
        from bng_tpu.telemetry.hist import LatencyHist

        st = ShardTelemetry(3, 4)
        for seed in range(5):
            self._rec(st, seed)
        merged = st.merged()
        for stage in ShardTelemetry.STAGES:
            fwd = LatencyHist()
            for shard in st.hists:
                fwd.merge(shard[stage])
            rev = LatencyHist()
            for shard in reversed(st.hists):
                rev.merge(shard[stage])
            assert np.array_equal(fwd.counts, rev.counts)
            assert np.array_equal(merged[stage].counts, fwd.counts)
            assert merged[stage].n == sum(sh[stage].n for sh in st.hists)

    def test_idle_shard_records_nothing(self):
        from bng_tpu.parallel.sharded import ShardTelemetry

        st = ShardTelemetry(2, 2)
        length = np.array([100, 100, 0, 0], dtype=np.uint32)
        st.record_fused(length, np.zeros(4, np.uint8), None, None, 0,
                        10.0, 1.0)
        assert st.hists[0]["total"].n == 1
        assert st.hists[1]["total"].n == 0  # idle shard: no lap

    def test_snapshot_is_json_serializable(self):
        from bng_tpu.parallel.sharded import ShardTelemetry

        st = ShardTelemetry(2, 4)
        self._rec(st, 1)
        json.dumps(st.snapshot())


# ---------------------------------------------------------------------------
# metrics export
# ---------------------------------------------------------------------------

class TestMetricsExport:
    def test_collect_slo_families(self, tmp_path):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        tr = tele.Tracer()
        mon = slo.SLOMonitor(tr, slos=(slo.SLOSpec("dispatch", 100.0),),
                             window_s=10.0, burn_windows=1)
        t = 0.0
        mon.tick(t)
        _feed(tr, tele.DISPATCH, 500.0)
        t += 11
        mon.tick(t)
        m.collect_slo(mon)
        text = m.expose()
        assert 'bng_slo_breaches_total{stage="dispatch"} 1' in text
        assert 'bng_slo_budget_us{stage="dispatch"} 100' in text
        assert "bng_slo_ok 1" in text  # breach re-armed -> not burning

    def test_collect_sharded_families(self):
        from bng_tpu.control.metrics import BNGMetrics
        from bng_tpu.parallel.sharded import ShardTelemetry

        class _FakeCluster:
            telemetry = ShardTelemetry(2, 2)

        cl = _FakeCluster()
        length = np.array([100, 100, 100, 0], dtype=np.uint32)
        verdict = np.array([2, 0, 3, 0], dtype=np.uint8)
        cl.telemetry.record_fused(length, verdict, None, None, 3,
                                  20.0, 2.0)
        m = BNGMetrics()
        m.collect_sharded(cl)
        text = m.expose()
        assert "bng_shard_psum_dhcp_hits_total 3" in text
        assert ('bng_shard_frames_total{shard="0",verdict="tx"} 1'
                in text)
        assert 'bng_shard_stage_p99_us{shard="0",stage="total"}' in text


class TestLoadtestResultField:
    def test_slo_field_rides_to_dict(self):
        from bng_tpu.loadtest.harness import BenchmarkResult

        res = BenchmarkResult()
        res.slo = {"ok": True, "breaches": []}
        assert res.to_dict()["slo"] == {"ok": True, "breaches": []}
