"""Storm-suite tests (bng_tpu/chaos/storms.py + the substrate it rides).

Fast deterministic variants of the five storms (same code, reduced
`scale`), the generator's byte-identity proof, the new invariant checks
(v6 lease-vs-pool, NAT block accounting, QoS mirror) with planted
violations, the expiry-batching/jitter engine changes, and the
exhaustion-hygiene counters. `make verify-storm` runs the `storm`
marker; the full-scale storms run under `bng chaos run` (verify-chaos
bit-determinism gate).
"""

from __future__ import annotations

import json

import pytest

from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import SERVER_IP, SERVER_MAC, _mac, _reply
from bng_tpu.chaos.storms import STORMS
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.loadtest.harness import (BenchmarkConfig, BenchmarkResult,
                                      StormFrameFactory)
from bng_tpu.utils.net import ip_to_u32, mac_to_u64

pytestmark = pytest.mark.storm

SEED = 123


# ---------------------------------------------------------------------------
# generator: template patch-in must be byte-identical to the codec
# ---------------------------------------------------------------------------

class TestStormFrameFactory:
    MAC = bytes.fromhex("02c500001a2b")
    IP = ip_to_u32("10.0.7.9")

    def test_discover_byte_identical(self):
        fac = StormFrameFactory(SERVER_IP)
        p = dhcp_codec.build_request(self.MAC, dhcp_codec.DISCOVER,
                                     xid=0x1234)
        ref = packets.udp_packet(self.MAC, b"\xff" * 6, 0, 0xFFFFFFFF,
                                 68, 67, p.encode().ljust(300, b"\x00"))
        assert fac.discover(self.MAC, 0x1234) == ref

    def test_request_byte_identical(self):
        fac = StormFrameFactory(SERVER_IP)
        p = dhcp_codec.build_request(self.MAC, dhcp_codec.REQUEST, xid=7,
                                     requested_ip=self.IP,
                                     server_id=SERVER_IP)
        ref = packets.udp_packet(self.MAC, b"\xff" * 6, 0, 0xFFFFFFFF,
                                 68, 67, p.encode().ljust(300, b"\x00"))
        assert fac.request(self.MAC, self.IP, 7) == ref

    def test_renew_byte_identical_incl_checksum(self):
        fac = StormFrameFactory(SERVER_IP)
        p = dhcp_codec.build_request(self.MAC, dhcp_codec.REQUEST, xid=9,
                                     ciaddr=self.IP)
        ref = packets.udp_packet(self.MAC, b"\xff" * 6, self.IP, SERVER_IP,
                                 68, 67, p.encode().ljust(300, b"\x00"))
        got = fac.renew(self.MAC, self.IP, 9)
        assert got == ref
        assert packets.decode(got).ip_checksum_ok

    def test_rendered_frames_decode_through_the_server_path(self):
        fac = StormFrameFactory(SERVER_IP)
        dec = packets.decode(fac.discover(self.MAC, 5))
        req = dhcp_codec.decode(dec.payload)
        assert req.msg_type == dhcp_codec.DISCOVER
        assert req.chaddr[:6] == self.MAC and req.xid == 5


# ---------------------------------------------------------------------------
# the five storms, reduced scale (same code as `bng chaos run`)
# ---------------------------------------------------------------------------

class TestStormsFast:
    def test_flash_crowd(self):
        r = STORMS["flash_crowd_reconnect"](SEED, scale=0.01)
        assert r["ok"], json.dumps(r, indent=1)
        assert r["req_after_offer_shed"] == 0
        assert r["unique_ips"] == r["leased"]
        assert sum(r["shed"].values()) > 0  # the storm actually shed
        assert r["workers_final"] > 4  # autoscaler grew under load
        assert r["calm_shed"] == 0  # admission recovered

    def test_lease_expiry_avalanche(self):
        r = STORMS["lease_expiry_avalanche"](SEED, scale=0.02)
        assert r["ok"], json.dumps(r, indent=1)
        assert r["cliff_expiries"] == 1
        assert all(s <= r["reap_budget"] for s in r["sweeps"])
        assert len(r["sweeps"]) >= 2  # the cliff took several ticks
        assert r["mid_cliff_doras"] == len(r["sweeps"])
        assert r["jitter_expiries"] >= r["jitter_buckets_min"]

    def test_cgnat_port_exhaustion(self):
        r = STORMS["cgnat_port_exhaustion"](SEED, scale=0.05)
        assert r["ok"], json.dumps(r, indent=1)
        # every refusal is a counted degraded verdict
        assert r["counted_block"] == r["blocks_refused"] > 0
        assert r["counted_port"] == r["flows_refused"] > 0
        assert r["reused_after_release"] > 0

    def test_coa_policy_flap(self):
        r = STORMS["coa_policy_flap"](SEED, scale=0.05)
        assert r["ok"], json.dumps(r, indent=1)
        assert r["renew_ok"] == r["renew_total"]
        assert r["coa_nak"] == r["flap_rounds"]
        assert r["bad_auth"] == r["flap_rounds"]

    def test_dual_stack_bringup_books_agree_with_bitmaps(self):
        """The satellite: after the storm, the v4 AND v6 lease books
        agree with their pool bitmaps for the same MAC set."""
        r = STORMS["dual_stack_bringup"](SEED, scale=0.1)
        assert r["ok"], json.dumps(r, indent=1)
        n = r["subscribers"]
        assert r["dual_stacked"] == n
        # v4: every lease is fleet-owned in the parent bitmap
        assert r["v4_pool_fleet_owned"] >= r["leased_v4"] == n
        # v6: bindings == allocations, both IA_NA and IA_PD
        assert r["v6_allocated_na"] == r["leased_v6_na"] == n
        assert r["v6_allocated_pd"] == r["leased_v6_pd"] == n
        assert r["ra_seen"] == r["rs_answered"] == n
        assert r["audit_ok"] and not r["violations"]

    def test_storms_deterministic(self):
        from bng_tpu.chaos import runner

        names = ["flash_crowd_reconnect", "lease_expiry_avalanche",
                 "cgnat_port_exhaustion", "dual_stack_bringup"]
        a = runner.canonical_json(runner.run_scenarios(
            seed=9, names=names, storm_scale=0.01))
        b = runner.canonical_json(runner.run_scenarios(
            seed=9, names=names, storm_scale=0.01))
        assert a == b
        assert json.loads(a)["ok"] is True


# ---------------------------------------------------------------------------
# new invariant checks: planted violations must be detected
# ---------------------------------------------------------------------------

class TestV6Audit:
    def _server(self):
        from bng_tpu.control.dhcpv6.server import (AddressPool6,
                                                   DHCPv6Server,
                                                   DHCPv6ServerConfig,
                                                   PrefixPool6)

        return DHCPv6Server(
            DHCPv6ServerConfig(server_mac=SERVER_MAC, rapid_commit=True),
            address_pool=AddressPool6("2001:db8:100::/64"),
            prefix_pool=PrefixPool6("2001:db8:f000::/40",
                                    delegated_len=56),
            clock=lambda: 1000.0)

    def _bind_one(self, srv):
        from bng_tpu.control.dhcpv6 import protocol as p6
        from bng_tpu.control.dhcpv6.protocol import (DHCPv6Message, IANA,
                                                     IAPD,
                                                     generate_duid_ll)

        m = DHCPv6Message(p6.SOLICIT, 1)
        m.add(p6.OPT_CLIENTID, generate_duid_ll(_mac(1)).encode())
        m.add_ia_na(IANA(1))
        m.add_ia_pd(IAPD(1))
        m.add(p6.OPT_RAPID_COMMIT, b"")
        assert srv.handle_message(m.encode()) is not None

    def test_clean_book_audits_clean(self):
        srv = self._server()
        self._bind_one(srv)
        report = audit_invariants(dhcpv6=srv, check_roundtrip=False)
        assert report.ok, report.to_dict()
        assert report.checks["v6_leases_na"] == 1
        assert report.checks["v6_leases_pd"] == 1

    def test_planted_unallocated_binding_detected(self):
        srv = self._server()
        self._bind_one(srv)
        lease = next(l for (d, i, pd), l in srv.leases.items() if not pd)
        srv.addr_pool._allocated.pop(lease.address)  # plant the leak
        report = audit_invariants(dhcpv6=srv, check_roundtrip=False)
        assert not report.ok
        assert "v6-lease-not-allocated" in report.violations_by_kind()

    def test_planted_orphan_allocation_detected(self):
        srv = self._server()
        self._bind_one(srv)
        srv.addr_pool.allocate()  # allocated, never bound
        report = audit_invariants(dhcpv6=srv, check_roundtrip=False)
        assert not report.ok
        assert "v6-alloc-orphan" in report.violations_by_kind()


class TestNATBlockAccounting:
    def _nat(self):
        from bng_tpu.control.nat import NATManager

        return NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                          ports_per_subscriber=64,
                          port_range=(1024, 1024 + 64 * 4 - 1),
                          sessions_nbuckets=256, sub_nat_nbuckets=64)

    def test_exhausted_allocator_audits_clean_and_counts(self):
        nat = self._nat()
        subs = [ip_to_u32("10.9.0.1") + i for i in range(6)]
        granted = [s for s in subs if nat.allocate_nat(s, 0)]
        assert len(granted) == 4
        assert nat.exhausted["block"] == 2
        report = audit_invariants(nat=nat, check_roundtrip=False)
        assert report.ok, report.to_dict()
        assert report.checks["nat_exhausted_block"] == 2

    def test_planted_block_leak_detected(self):
        nat = self._nat()
        subs = [ip_to_u32("10.9.0.1") + i for i in range(3)]
        for s in subs:
            nat.allocate_nat(s, 0)
        # plant the leak: drop a block without returning it to the free
        # list (carved != allocated + free)
        leaked = nat.blocks.pop(subs[0])
        nat.sub_nat.delete([subs[0]])
        report = audit_invariants(nat=nat, check_roundtrip=False)
        assert not report.ok
        assert "nat-block-accounting" in report.violations_by_kind()
        assert leaked["port_start"] >= 1024


# ---------------------------------------------------------------------------
# expiry batching + lease jitter (the engine half of the avalanche)
# ---------------------------------------------------------------------------

class TestExpiryBatching:
    def _server(self, n=40, jitter=0.0):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.pool import Pool, PoolManager

        pools = PoolManager()
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=20, gateway=SERVER_IP,
                            lease_time=600))
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            clock=lambda: 1000.0,
                            lease_jitter_frac=jitter)
        fac = StormFrameFactory(SERVER_IP)
        for i in range(n):
            m = _mac(9000 + i)
            off = server.handle_frame(fac.discover(m, i))
            server.handle_frame(fac.request(m, _reply(off).yiaddr, n + i))
        return server

    def test_max_reaps_bounds_each_sweep(self):
        server = self._server(n=40)
        assert len({l.expiry for l in server.leases.values()}) == 1
        sweeps = []
        while server.leases:
            sweeps.append(server.cleanup_expired(10_000, max_reaps=16))
        assert sweeps == [16, 16, 8]
        # the partially-reaped intermediate states stayed consistent
        # (proved against the pools the sweep releases into)
        assert sum(sweeps) == 40

    def test_unbounded_default_reaps_everything(self):
        server = self._server(n=10)
        assert server.cleanup_expired(10_000) == 10

    def test_partial_reap_state_is_audit_clean(self):
        server = self._server(n=30)
        server.cleanup_expired(10_000, max_reaps=7)
        report = audit_invariants(pools=server.pools, dhcp=server,
                                  check_roundtrip=False)
        assert report.ok, report.to_dict()

    def test_jitter_spreads_the_cliff_and_only_extends(self):
        server = self._server(n=64, jitter=0.5)
        exps = sorted({l.expiry for l in server.leases.values()})
        assert len(exps) >= server.LEASE_JITTER_BUCKETS // 2
        assert exps[0] >= 1000 + 600  # never shortened
        assert exps[-1] <= 1000 + 600 * 2  # bounded by lt*(1+frac)
        # quantized: at most BUCKETS distinct values (template cache
        # stays bounded)
        assert len(exps) <= server.LEASE_JITTER_BUCKETS

    def test_jitter_is_deterministic_per_mac(self):
        a = self._server(n=16, jitter=0.5)
        b = self._server(n=16, jitter=0.5)
        ea = {mk: l.expiry for mk, l in a.leases.items()}
        eb = {mk: l.expiry for mk, l in b.leases.items()}
        assert ea == eb

    def test_client_is_told_the_jittered_lease_time(self):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.pool import Pool, PoolManager

        pools = PoolManager()
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=24, gateway=SERVER_IP,
                            lease_time=600))
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            clock=lambda: 1000.0, lease_jitter_frac=0.5)
        fac = StormFrameFactory(SERVER_IP)
        m = _mac(4242)
        off = server.handle_frame(fac.discover(m, 1))
        ack = _reply(server.handle_frame(
            fac.request(m, _reply(off).yiaddr, 2)))
        opt = dict(ack.options)[dhcp_codec.OPT_LEASE_TIME]
        told = int.from_bytes(opt, "big")
        lease = server.leases[mac_to_u64(m)]
        # server expiry and the client's advertised lease time agree —
        # jitter must never strand a renewal
        assert lease.expiry == 1000 + told

    def test_dhcpv6_bounded_cleanup(self):
        from bng_tpu.control.dhcpv6.server import (AddressPool6,
                                                   DHCPv6Server,
                                                   DHCPv6ServerConfig,
                                                   Lease6)

        srv = DHCPv6Server(DHCPv6ServerConfig(server_mac=SERVER_MAC),
                           address_pool=AddressPool6("2001:db8:100::/64"),
                           clock=lambda: 1000.0)
        for i in range(9):
            addr = srv.addr_pool.allocate()
            srv.leases[(b"d%d" % i, 1, False)] = Lease6(
                b"d%d" % i, 1, addr, 128, expiry=500.0)
        assert srv.cleanup_expired(1000.0, max_reaps=4) == 4
        assert srv.cleanup_expired(1000.0, max_reaps=4) == 4
        assert srv.cleanup_expired(1000.0) == 1
        assert not srv.leases and not srv.addr_pool._allocated


# ---------------------------------------------------------------------------
# exhaustion hygiene: counted + exposed, never silent
# ---------------------------------------------------------------------------

class TestExhaustionHygiene:
    def test_dhcp_pool_exhaustion_counted(self):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.pool import Pool, PoolManager

        pools = PoolManager()
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=30, gateway=ip_to_u32("10.0.0.1"),
                            lease_time=600))  # 1 usable address
        server = DHCPServer(SERVER_MAC, ip_to_u32("10.0.0.1"), pools,
                            clock=lambda: 1000.0)
        fac = StormFrameFactory(ip_to_u32("10.0.0.1"))
        assert server.handle_frame(fac.discover(_mac(1), 1)) is not None
        # second client: pool dry -> silent per protocol, COUNTED here
        assert server.handle_frame(fac.discover(_mac(2), 2)) is None
        assert server.stats.pool_exhausted == 1

    def test_dhcpv6_exhaustion_counted(self):
        from bng_tpu.control.dhcpv6 import protocol as p6
        from bng_tpu.control.dhcpv6.protocol import (DHCPv6Message, IANA,
                                                     generate_duid_ll)
        from bng_tpu.control.dhcpv6.server import (AddressPool6,
                                                   DHCPv6Server,
                                                   DHCPv6ServerConfig)

        srv = DHCPv6Server(
            DHCPv6ServerConfig(server_mac=SERVER_MAC, rapid_commit=True),
            address_pool=AddressPool6("2001:db8:100::/126"),  # 2 usable
            clock=lambda: 1000.0)
        for i in range(5):
            m = DHCPv6Message(p6.SOLICIT, i + 1)
            m.add(p6.OPT_CLIENTID, generate_duid_ll(_mac(i)).encode())
            m.add_ia_na(IANA(1))
            m.add(p6.OPT_RAPID_COMMIT, b"")
            srv.handle_message(m.encode())
        assert srv.stats.addr_exhausted == 3
        assert srv.stats.no_addrs == 3

    def test_metrics_family_exposed(self):
        from bng_tpu.control.metrics import BNGMetrics
        from bng_tpu.control.nat import NATManager

        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         ports_per_subscriber=64,
                         port_range=(1024, 1024 + 63),
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        assert nat.allocate_nat(ip_to_u32("10.1.0.1"), 0) is not None
        assert nat.allocate_nat(ip_to_u32("10.1.0.2"), 0) is None
        m = BNGMetrics()
        m.collect_exhaustion(nat=nat)
        text = m.expose()
        assert 'bng_pool_exhausted_total{resource="nat_block"} 1' in text

    def test_fleet_slice_exhaustion_monotonic_across_resize(self):
        """bng_pool_exhausted_total{resource=fleet_slice} is a COUNTER:
        a resize restarts per-worker ServerStats at 0, so the exposed
        total must come from the fleet's monotonic fold, never move
        backward, and keep counting in the new worker generation."""
        from bng_tpu.chaos.storms import _build_storm_fleet

        fleet, pools, fastpath = _build_storm_fleet(
            2, lambda: 1000.0, prefix_len=29,  # 6 usable addrs total
            sub_nbuckets=256, slice_size=2, inbox=64)
        fac = StormFrameFactory(SERVER_IP)
        # drive DISCOVERs until the slices + parent pool run dry
        out = fleet.handle_batch(
            [(i, fac.discover(_mac(7000 + i), i + 1)) for i in range(24)],
            now=1000.0)
        exhausted = fleet.pool_exhausted_total()
        assert exhausted > 0
        assert sum(1 for _l, r in out if r is None) == exhausted
        fleet.resize(3)  # per-worker stats restart at 0
        assert fleet.pool_exhausted_total() >= exhausted  # never backward
        out2 = fleet.handle_batch(
            [(i, fac.discover(_mac(7100 + i), 100 + i)) for i in range(8)],
            now=1001.0)
        assert any(r is None for _l, r in out2)
        assert fleet.pool_exhausted_total() > exhausted  # still counting
        assert (fleet.stats_snapshot()["pool_exhausted_total"]
                == fleet.pool_exhausted_total())

    def test_benchmark_result_carries_scenario_shed_degraded(self):
        res = BenchmarkResult(scenario="flash_crowd",
                              shed={"inbox_full": 3},
                              degraded={"dhcp_pool": 2})
        d = res.to_dict()
        assert d["scenario"] == "flash_crowd"
        assert d["shed"] == {"inbox_full": 3}
        assert d["degraded"] == {"dhcp_pool": 2}
        assert "Shed:" in res.summary()
        assert BenchmarkConfig(scenario="x").scenario == "x"


# ---------------------------------------------------------------------------
# QoS host/device mirror audit (the CoA-flap checker) — planted divergence
# ---------------------------------------------------------------------------

class TestQosMirrorAudit:
    def _engine_with_qos(self):
        from bng_tpu.chaos.scenarios import _build_server_stack
        from bng_tpu.runtime.engine import Engine, QoSTables

        server, pools, fastpath, nat = _build_server_stack(
            lambda: 1000.0)
        qos = QoSTables()
        eng = Engine(fastpath, nat, qos=qos, batch_size=32,
                     slow_path=server.handle_frame)
        qos.set_subscriber(ip_to_u32("10.0.1.5"), 100_000_000, 20_000_000)
        eng.process([])  # drain the row to the device
        return eng, qos, server, pools, nat

    def test_clean_mirror_audits_clean(self):
        eng, qos, server, pools, nat = self._engine_with_qos()
        report = audit_invariants(engine=eng, pools=pools, dhcp=server,
                                  nat=nat, check_roundtrip=False)
        assert report.ok, report.to_dict()
        assert "mirror_slots.qos.up" in report.checks

    def test_planted_config_divergence_detected(self):
        from bng_tpu.ops.qtable import QW_BURST

        eng, qos, server, pools, nat = self._engine_with_qos()
        slot = qos.up._find(ip_to_u32("10.0.1.5"))
        # corrupt a host CONFIG word without marking the slot dirty —
        # the drain will never ship it, so host and device now disagree
        qos.up.rows[slot][QW_BURST] += 1
        report = audit_invariants(engine=eng, pools=pools, dhcp=server,
                                  nat=nat, check_roundtrip=False)
        assert not report.ok
        assert "qos-mirror-mismatch" in report.violations_by_kind()

    def test_device_token_words_are_exempt(self):
        from bng_tpu.ops.qtable import QW_TOKENS

        eng, qos, server, pools, nat = self._engine_with_qos()
        slot = qos.up._find(ip_to_u32("10.0.1.5"))
        # token words are device-authoritative — host drift there is
        # EXPECTED (fold_device_authoritative owns it), never a finding
        qos.up.rows[slot][QW_TOKENS] += 7
        report = audit_invariants(engine=eng, pools=pools, dhcp=server,
                                  nat=nat, check_roundtrip=False)
        assert report.ok, report.to_dict()


# ---------------------------------------------------------------------------
# runner + CLI integration
# ---------------------------------------------------------------------------

class TestRunnerAndCLI:
    def test_catalog_covers_every_scenario(self):
        from bng_tpu.chaos.runner import ALL_SCENARIOS, scenario_catalog

        cat = dict(scenario_catalog())
        assert set(cat) == set(ALL_SCENARIOS)
        assert all(desc for desc in cat.values())
        for storm in STORMS:
            assert storm in cat

    def test_unknown_scenario_raises_with_names(self):
        from bng_tpu.chaos import runner

        with pytest.raises(ValueError, match="flash_crowd_reconnect"):
            runner.run_scenarios(seed=1, names=["nope"])

    def test_cli_list_prints_catalog(self, capsys):
        from bng_tpu.cli import main

        assert main(["chaos", "run", "--list"]) == 0
        out = capsys.readouterr().out
        for storm in STORMS:
            assert storm in out

    def test_cli_unknown_scenario_rc2_with_catalog(self, capsys):
        from bng_tpu.cli import main

        assert main(["chaos", "run", "--scenario", "nope"]) == 2
        err = capsys.readouterr().err
        assert "scenario catalog" in err
        assert "flash_crowd_reconnect" in err

    def test_cli_storm_scale_and_bench_log(self, tmp_path, capsys):
        from bng_tpu.cli import main

        log = tmp_path / "bench_runs.jsonl"
        rc = main(["chaos", "run", "--seed", "5",
                   "--scenario", "cgnat_port_exhaustion",
                   "--storm-scale", "0.05",
                   "--bench-log", str(log)])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"]
        assert out["storm_scale"] == 0.05
        lines = [json.loads(l) for l in log.read_text().splitlines()]
        assert len(lines) == 1
        assert lines[0]["scenario"] == "cgnat_port_exhaustion"
        assert lines[0]["degraded"]["nat_block"] > 0
        assert "ts" in lines[0]
