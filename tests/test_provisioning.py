"""Tests for deviceauth, ztp, agent, pon, direct (L5 provisioning layer)."""

import os
import subprocess
import time

import pytest

from bng_tpu.control.agent import Agent, AgentConfig, AgentState
from bng_tpu.control.deviceauth import (
    AuthMode, DeviceIdentity, MTLSAuthenticator, NoneAuthenticator,
    PSKAuthenticator, AuthenticatedTransport, cert_fingerprint, cert_not_after,
    generate_device_id, new_authenticator, read_device_identity, sanitize_id,
)
from bng_tpu.control.direct import (
    BindingEvent, DirectAuthenticator, DirectConfig, ONTMapping, StubBSSClient,
)
from bng_tpu.control.nexus import (
    NexusClient, NTEEntity, SubscriberEntity, VLANAllocator,
)
from bng_tpu.control.pon import (
    DiscoveryEvent, NTEState, PONConfig, PONManager,
)
from bng_tpu.control.subscriber import SessionKind, SubscriberManager
from bng_tpu.control.ztp import (
    BootstrapClient, BootstrapConfig, BootstrapPending, build_vendor_option,
    discover_from_lease, extract_nexus_url, parse_vendor_options,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ----------------------------------------------------------- deviceauth

class TestDeviceAuth:
    def test_sanitize_and_device_id(self):
        assert sanitize_id("AB/CD 12!") == "ab-cd-12-"
        assert generate_device_id("SN123", "") == "dev-sn123"
        assert generate_device_id("", "02:aa:bb:cc:dd:01") == "dev-02aabbccdd01"
        assert generate_device_id("", "").startswith("dev-")

    def test_read_identity_from_fake_sysfs(self, tmp_path):
        dmi = tmp_path / "sys/class/dmi/id"
        dmi.mkdir(parents=True)
        (dmi / "product_serial").write_text("SER-42\n")
        (dmi / "product_name").write_text("edge-box\n")
        net = tmp_path / "sys/class/net/eth0"
        net.mkdir(parents=True)
        (net / "address").write_text("02:aa:bb:cc:dd:ee\n")
        ident = read_device_identity(str(tmp_path))
        assert ident.serial == "SER-42"
        assert ident.mac == "02:aa:bb:cc:dd:ee"
        assert ident.model == "edge-box"
        assert ident.device_id == "dev-ser-42"

    def test_psk_sign_verify_roundtrip(self):
        clk = FakeClock(1_700_000_000.0)
        ident = DeviceIdentity(device_id="dev-a", serial="S1", mac="02:00:00:00:00:01")
        client = PSKAuthenticator(psk="super-secret-key-16", identity=ident,
                                  clock=clk)
        server = PSKAuthenticator(psk="super-secret-key-16", clock=clk)
        h = client.http_headers()
        assert h["X-Device-ID"] == "dev-a" and h["X-Device-MAC"]
        server.verify_signature(h["X-Device-ID"], h["X-Device-Timestamp"],
                                h["X-Device-Signature"])

    def test_psk_verify_rejects_skew_and_forgery(self):
        clk = FakeClock(1_700_000_000.0)
        client = PSKAuthenticator(psk="super-secret-key-16",
                                  identity=DeviceIdentity(device_id="d"),
                                  clock=clk)
        h = client.http_headers()
        clk.advance(600)  # beyond MaxTimestampSkew
        with pytest.raises(ValueError, match="skew"):
            client.verify_signature("d", h["X-Device-Timestamp"],
                                    h["X-Device-Signature"])
        clk.advance(-600)
        with pytest.raises(ValueError, match="mismatch"):
            client.verify_signature("d", h["X-Device-Timestamp"], "00" * 32)

    def test_psk_rotation_and_minimum_length(self):
        with pytest.raises(ValueError):
            PSKAuthenticator(psk="short")
        a = PSKAuthenticator(psk="super-secret-key-16")
        sig_old = a.sign_message("m")
        a.rotate_psk("another-secret-key-32chars")
        assert a.sign_message("m") != sig_old
        with pytest.raises(ValueError):
            a.rotate_psk("short")

    def test_none_authenticator_and_dispatch(self):
        a = new_authenticator("none", identity=DeviceIdentity(device_id="x"))
        assert isinstance(a, NoneAuthenticator)
        assert a.authenticate().success and a.mode == AuthMode.NONE
        assert a.http_headers()["X-Device-ID"] == "x"

    def test_authenticated_transport_injects_headers(self):
        seen = {}

        def base(method, url, headers, body):
            seen.update(headers)
            return 200

        t = AuthenticatedTransport(base, PSKAuthenticator(
            psk="super-secret-key-16", identity=DeviceIdentity(device_id="d")))
        assert t("GET", "http://nexus/api", {"Accept": "json"}) == 200
        assert seen["X-Device-ID"] == "d" and "X-Device-Signature" in seen
        assert seen["Accept"] == "json"


@pytest.fixture(scope="module")
def cert_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "dev.crt"), str(d / "dev.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
         "ec_paramgen_curve:P-256", "-nodes", "-keyout", key, "-out", cert,
         "-days", "30", "-subj", "/CN=device-001"],
        check=True, capture_output=True, timeout=60)
    return cert, key


class TestMTLS:
    def test_cert_parsing(self, cert_pair):
        cert, _ = cert_pair
        pem = open(cert).read()
        not_after = cert_not_after(pem)
        # ~30 days out
        assert 25 * 86400 < not_after - time.time() < 35 * 86400
        assert len(cert_fingerprint(pem)) == 64

    def test_mtls_authenticator(self, cert_pair):
        cert, key = cert_pair
        a = MTLSAuthenticator(cert, key)
        assert a.mode == AuthMode.MTLS
        assert a.authenticate().success
        assert not a.expires_within(86400)
        assert a.expires_within(40 * 86400)
        assert a.identity.device_id == "dev-device-001"
        assert a.http_headers()["X-Device-Cert-Fingerprint"] == a.fingerprint

    def test_rotation_reload(self, cert_pair, tmp_path):
        cert, key = cert_pair
        a = MTLSAuthenticator(cert, key)
        assert not a.maybe_rotate()  # unchanged
        os.utime(cert, (time.time() + 5, time.time() + 5))
        assert a.maybe_rotate()

    def test_tls_config_builds(self, cert_pair):
        cert, key = cert_pair
        ctx = MTLSAuthenticator(cert, key).tls_config()
        import ssl
        assert isinstance(ctx, ssl.SSLContext)


# ------------------------------------------------------------------ ztp

class TestZTP:
    def test_option_224_priority(self):
        opts = {224: b"https://nexus.isp.net",
                43: build_vendor_option("https://other")}
        assert extract_nexus_url(opts) == "https://nexus.isp.net"

    def test_option_43_tlv(self):
        data = bytes([9, 2, 0, 0]) + build_vendor_option("https://n")
        assert parse_vendor_options(data) == "https://n"
        assert parse_vendor_options(b"\x01\xff") == ""  # truncated

    def test_discover_from_lease(self):
        r = discover_from_lease(ip="10.0.0.9", gateway="10.0.0.1",
                                options={43: build_vendor_option("https://n")})
        assert r.nexus_url == "https://n" and r.ip == "10.0.0.9"

    def test_bootstrap_pending_then_configured(self):
        clk = FakeClock()
        sleeps = []
        responses = [
            ConnectionError("down"),
            {"status": "pending", "retry_after": 7},
            {"status": "pending"},
            {"status": "configured", "node_id": "bng-7", "site_id": "site-1",
             "role": "edge"},
        ]

        def transport(req):
            assert req.serial == "SER-1"
            r = responses.pop(0)
            if isinstance(r, Exception):
                raise r
            return r

        c = BootstrapClient(
            BootstrapConfig(initial_backoff=2.0), transport,
            identity=DeviceIdentity(device_id="d", serial="SER-1",
                                    mac="02:00:00:00:00:01"),
            clock=clk, sleep=sleeps.append)
        cfg = c.bootstrap()
        assert cfg.node_id == "bng-7" and cfg.role == "edge"
        assert sleeps == [2.0, 7, 2.0]  # net-error backoff, server hint, reset

    def test_bootstrap_max_retries(self):
        c = BootstrapClient(
            BootstrapConfig(max_retries=2), lambda req: {"status": "pending"},
            identity=DeviceIdentity(device_id="d", serial="S"),
            clock=FakeClock(), sleep=lambda s: None)
        with pytest.raises(TimeoutError):
            c.bootstrap()


# ---------------------------------------------------------------- agent

class TestAgent:
    def _nexus(self):
        n = NexusClient()
        n.subscribers.put("s1", SubscriberEntity(
            id="s1", mac="02:aa:bb:cc:dd:01", isp_id="isp-a", nte_id="ONT1"))
        n.subscribers.put("s2", SubscriberEntity(id="s2", isp_id="isp-b"))
        n.ntes.put("ONT1", NTEEntity(id="ONT1", serial="ONT1"))
        return n

    def test_start_syncs_and_goes_online(self):
        a = Agent(AgentConfig(device_id="dev-1"), self._nexus())
        states = []
        a.on_state_change = lambda old, new: states.append(new)
        a.start()
        assert a.state == AgentState.ONLINE
        assert AgentState.SYNCING in states
        assert a.subscriber_count() == 2
        assert a.get_subscriber_by_mac("02:AA:BB:CC:DD:01").id == "s1"
        assert a.get_subscriber_by_nte("ONT1").id == "s1"
        assert a.nte_count() == 1

    def test_watcher_keeps_cache_warm(self):
        n = self._nexus()
        a = Agent(AgentConfig(device_id="dev-1"), n)
        a.start()
        n.subscribers.put("s3", SubscriberEntity(id="s3", mac="02:00:00:00:00:03"))
        assert a.get_subscriber("s3") is not None
        n.subscribers.delete("s1")
        assert a.get_subscriber("s1") is None
        assert a.get_subscriber_by_mac("02:aa:bb:cc:dd:01") is None

    def test_isp_churn_event(self):
        n = self._nexus()
        a = Agent(AgentConfig(device_id="dev-1"), n)
        a.start()
        churns = []
        a.on_isp_churn = lambda sid, old, new: churns.append((sid, old, new))
        n.subscribers.put("s1", SubscriberEntity(
            id="s1", mac="02:aa:bb:cc:dd:01", isp_id="isp-z", nte_id="ONT1"))
        assert churns == [("s1", "isp-a", "isp-z")]
        assert a.subscriber_count_by_isp() == {"isp-z": 1, "isp-b": 1}

    def test_heartbeat_and_degradation(self):
        clk = FakeClock()
        n = NexusClient(clock=clk)
        from bng_tpu.control.nexus import DeviceEntity
        n.devices.put("dev-1", DeviceEntity(id="dev-1", state="approved"))
        a = Agent(AgentConfig(device_id="dev-1", degraded_after=60), n, clock=clk)
        a.start()
        assert a.heartbeat()
        assert n.devices.get("dev-1").last_heartbeat == clk.t
        clk.advance(120)
        a.tick()
        assert a.state == AgentState.DEGRADED
        assert a.heartbeat()  # recovery
        assert a.state == AgentState.ONLINE
        assert a.health()["heartbeats"] == 2


# ------------------------------------------------------------------ pon

class TestPON:
    def _mgr(self, require_approval=True):
        n = NexusClient()
        vlans = VLANAllocator(s_tag_range=(100, 200), c_tag_range=(1, 100))
        m = PONManager(PONConfig(require_approval=require_approval), n, vlans)
        return m, n

    def test_unknown_ont_registers_pending(self):
        m, n = self._mgr()
        assert m.handle_discovery(DiscoveryEvent(serial="ONT-X")) is None
        assert m.get_state("ONT-X") == NTEState.PENDING_APPROVAL
        assert n.ntes.get("ONT-X").approved is False
        assert len(m.list_pending()) == 1

    def test_approval_triggers_provisioning(self):
        m, n = self._mgr()
        results = []
        m.on_provisioned = results.append
        m.handle_discovery(DiscoveryEvent(serial="ONT-X"))
        nte = n.ntes.get("ONT-X")
        nte.approved = True
        n.ntes.put("ONT-X", nte)  # operator approves in Nexus
        assert m.get_state("ONT-X") == NTEState.CONNECTED
        assert results and results[0].success
        assert results[0].s_tag and results[0].c_tag
        assert n.ntes.get("ONT-X").state == "connected"
        assert m.list_connected() == ["ONT-X"]

    def test_preapproved_provisions_immediately(self):
        m, n = self._mgr()
        n.ntes.put("ONT-Y", NTEEntity(id="ONT-Y", serial="ONT-Y", approved=True,
                                      s_tag=150, c_tag=7))
        r = m.handle_discovery(DiscoveryEvent(serial="ONT-Y"))
        assert r.success and (r.s_tag, r.c_tag) == (150, 7)

    def test_no_approval_mode(self):
        m, n = self._mgr(require_approval=False)
        n.ntes.put("ONT-Z", NTEEntity(id="ONT-Z", serial="ONT-Z"))
        assert m.handle_discovery(DiscoveryEvent(serial="ONT-Z")).success

    def test_disconnect(self):
        m, n = self._mgr(require_approval=False)
        n.ntes.put("ONT-Z", NTEEntity(id="ONT-Z", serial="ONT-Z"))
        m.handle_discovery(DiscoveryEvent(serial="ONT-Z"))
        gone = []
        m.on_disconnected = gone.append
        m.handle_disconnect("ONT-Z")
        assert m.get_state("ONT-Z") == NTEState.DISCONNECTED
        assert n.ntes.get("ONT-Z").state == "disconnected"
        assert gone == ["ONT-Z"]


# ---------------------------------------------------------------- direct

class TestDirectAuth:
    def _nexus(self):
        n = NexusClient()
        n.subscribers.put("s1", SubscriberEntity(
            id="s1", mac="02:aa:bb:cc:dd:01", circuit_id="olt1/1/1",
            nte_id="ONT1", isp_id="isp-a", qos_policy="residential-100mbps"))
        n.ntes.put("ONT1", NTEEntity(id="ONT1", serial="ONT1", s_tag=100, c_tag=5))
        return n

    def test_lookup_cascade_nexus(self):
        clk = FakeClock()
        auth = DirectAuthenticator(nexus=self._nexus(), clock=clk)
        m = auth.lookup(circuit_id="olt1/1/1")
        assert m.subscriber_id == "s1" and m.s_tag == 100
        assert auth.stats["nexus_lookups"] == 1
        # second hit comes from cache
        assert auth.lookup(circuit_id="olt1/1/1").subscriber_id == "s1"
        assert auth.stats["cache_hits"] == 1
        # TTL expiry forces re-lookup
        clk.advance(301)
        auth.lookup(circuit_id="olt1/1/1")
        assert auth.stats["nexus_lookups"] == 2

    def test_bss_fallback_and_sync(self):
        bss = StubBSSClient([ONTMapping(ont_serial="ONT9", circuit_id="c9",
                                        subscriber_id="s9", isp_id="isp-b")])
        auth = DirectAuthenticator(nexus=NexusClient(), bss=bss)
        assert auth.lookup(serial="ONT9").subscriber_id == "s9"
        assert auth.stats["bss_lookups"] == 1
        assert auth.sync_from_bss() == 1

    def test_subscriber_manager_integration(self):
        auth = DirectAuthenticator(nexus=self._nexus())
        mgr = SubscriberManager(authenticator=auth)
        s = mgr.create_session(SessionKind.IPOE, mac="02:aa:bb:cc:dd:01",
                               circuit_id="olt1/1/1")
        assert mgr.authenticate(s.id)
        assert s.subscriber_id == "s1"
        assert s.attributes["qos_policy"] == "residential-100mbps"

    def test_unknown_goes_to_walled_garden(self):
        auth = DirectAuthenticator(nexus=NexusClient())
        mgr = SubscriberManager(authenticator=auth)
        s = mgr.create_session(SessionKind.IPOE, mac="02:00:00:00:00:99")
        assert not mgr.authenticate(s.id)
        assert s.walled

    def test_binding_events_reported(self):
        bss = StubBSSClient([ONTMapping(ont_serial="ONT9", subscriber_id="s9")])
        auth = DirectAuthenticator(nexus=NexusClient(), bss=bss)
        mgr = SubscriberManager(authenticator=auth)
        s = mgr.create_session(SessionKind.IPOE, mac="02:00:00:00:00:01")
        s.attributes["ont_serial"] = "ONT9"
        mgr.authenticate(s.id)
        kinds = [e.event_type for e in bss.events]
        assert kinds == ["bind"]
        # rejection also reported
        s2 = mgr.create_session(SessionKind.IPOE, mac="02:00:00:00:00:02")
        mgr.authenticate(s2.id)
        assert [e.event_type for e in bss.events] == ["bind", "reject"]

    def test_disabled_mapping_rejected(self):
        bss = StubBSSClient([ONTMapping(ont_serial="ONT9", subscriber_id="s9",
                                        enabled=False)])
        auth = DirectAuthenticator(bss=bss)
        mgr = SubscriberManager(authenticator=auth)
        s = mgr.create_session(SessionKind.IPOE)
        s.attributes["ont_serial"] = "ONT9"
        assert not mgr.authenticate(s.id)
