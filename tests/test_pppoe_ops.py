"""Device-side PPPoE encap/decap + QinQ push/pop (ops.pppoe).

Round-trips against the host PPPoE codec (control.pppoe.codec) the same
way the DHCP kernel tests round-trip against dhcp_codec: the host builds
wire-correct frames, the device op transforms them, the host codec
re-parses the result.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bng_tpu.control.pppoe import codec
from bng_tpu.control import packets
from bng_tpu.ops import pppoe as P
from bng_tpu.ops.parse import parse_batch
from bng_tpu.ops.table import HostTable, TableGeom
from bng_tpu.utils.net import ip_to_u32

CLIENT_MAC = bytes.fromhex("02c0ffee0101")
AC_MAC = bytes.fromhex("02aabbccdd01")
SID = 0x0042
CLIENT_IP = ip_to_u32("10.0.0.50")


def session_tables():
    """by-session-id and by-ip tables holding one bound session."""
    by_sid = HostTable(64, key_words=1, val_words=P.PPPOE_WORDS, stash=8, name="pppoe_sid")
    by_ip = HostTable(64, key_words=1, val_words=P.PPPOE_WORDS, stash=8, name="pppoe_ip")
    mac_hi = int.from_bytes(CLIENT_MAC[:2], "big")
    mac_lo = int.from_bytes(CLIENT_MAC[2:], "big")
    row = np.zeros((P.PPPOE_WORDS,), dtype=np.uint32)
    row[P.PS_SESSION_ID] = SID
    row[P.PS_MAC_HI] = mac_hi
    row[P.PS_MAC_LO] = mac_lo
    row[P.PS_IP] = CLIENT_IP
    by_sid.insert([SID], row)
    by_ip.insert([CLIENT_IP], row)
    return by_sid, by_ip


def batch(frames, L=512):
    B = max(len(frames), 4)
    pkt = np.zeros((B, L), dtype=np.uint8)
    ln = np.zeros((B,), dtype=np.uint32)
    for i, f in enumerate(frames):
        pkt[i, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        ln[i] = len(f)
    return jnp.asarray(pkt), jnp.asarray(ln)


def ipv4_udp_payload():
    """A raw IPv4 packet (no L2) built via the packets helper."""
    full = packets.udp_packet(CLIENT_MAC, AC_MAC, CLIENT_IP,
                              ip_to_u32("8.8.8.8"), 40000, 53, b"q" * 32)
    return full[14:]  # strip Ethernet


def pppoe_data_frame(vlans=None, sid=SID, proto=P.PPP_IPV4):
    ip = ipv4_udp_payload()
    ppp = codec.ppp_frame(proto, ip)
    pppoe = codec.PPPoEPacket(code=0, session_id=sid, payload=ppp).encode()
    return codec.eth_frame(AC_MAC, CLIENT_MAC, codec.ETH_PPPOE_SESSION, pppoe,
                           vlans=vlans)


class TestDecap:
    @pytest.mark.parametrize("vlans", [None, [100], [100, 200]])
    def test_decap_strips_framing(self, vlans):
        by_sid, _ = session_tables()
        frame = pppoe_data_frame(vlans=vlans)
        pkt, ln = batch([frame])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert bool(res.done[0])
        out = bytes(np.asarray(res.out_pkt)[0][: int(res.out_len[0])])
        assert len(out) == len(frame) - P.PPPOE_HDR
        # re-parse: normal IPv4 frame now, same VLANs preserved
        d = packets.decode(out)
        assert d.ethertype == 0x0800
        assert d.src_ip == CLIENT_IP and d.dst_port == 53
        if vlans:
            _, _, _, _, tags = codec.parse_eth_vlan(out)
            assert tags == vlans
        assert int(res.src_ip_hint[0]) == CLIENT_IP
        assert int(res.stats[P.PST_DECAP]) == 1

    def test_unknown_session_punts(self):
        by_sid, _ = session_tables()
        frame = pppoe_data_frame(sid=0x999)
        pkt, ln = batch([frame])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert not bool(res.done[0]) and bool(res.punt[0])
        assert int(res.stats[P.PST_MISS]) == 1

    def test_wrong_mac_punts(self):
        by_sid, _ = session_tables()
        ip = ipv4_udp_payload()
        ppp = codec.ppp_frame(P.PPP_IPV4, ip)
        pppoe = codec.PPPoEPacket(code=0, session_id=SID, payload=ppp).encode()
        frame = codec.eth_frame(AC_MAC, bytes.fromhex("02dead00beef"),
                                codec.ETH_PPPOE_SESSION, pppoe)
        pkt, ln = batch([frame])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert not bool(res.done[0]) and bool(res.punt[0])

    def test_lcp_control_punts(self):
        by_sid, _ = session_tables()
        lcp = codec.ppp_frame(0xC021, b"\x09\x01\x00\x08\x00\x00\x00\x00")
        pppoe = codec.PPPoEPacket(code=0, session_id=SID, payload=lcp).encode()
        frame = codec.eth_frame(AC_MAC, CLIENT_MAC, codec.ETH_PPPOE_SESSION, pppoe)
        pkt, ln = batch([frame])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert not bool(res.done[0]) and bool(res.punt[0])
        assert int(res.stats[P.PST_CTRL_PUNT]) == 1

    def test_discovery_punts(self):
        by_sid, _ = session_tables()
        padi = codec.eth_frame(b"\xff" * 6, CLIENT_MAC,
                               codec.ETH_PPPOE_DISCOVERY,
                               bytes([0x11, 0x09, 0, 0, 0, 0]))
        pkt, ln = batch([padi])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert bool(res.punt[0]) and not bool(res.done[0])


class TestEncap:
    def test_encap_roundtrip(self):
        by_sid, by_ip = session_tables()
        # downstream IPv4 frame toward the subscriber
        down = packets.udp_packet(AC_MAC, CLIENT_MAC, ip_to_u32("8.8.8.8"),
                                  CLIENT_IP, 53, 40000, b"r" * 40)
        pkt, ln = batch([down])
        par = parse_batch(pkt, ln)
        res = P.pppoe_encap(pkt, ln, par.vlan_offset, par.ethertype,
                            par.dst_ip, by_ip.device_state(), TableGeom(64, 8),
                            server_mac=None)
        assert bool(res.done[0])
        out = bytes(np.asarray(res.out_pkt)[0][: int(res.out_len[0])])
        assert len(out) == len(down) + P.PPPOE_HDR
        dst, src, et, payload = codec.parse_eth(out)
        assert et == codec.ETH_PPPOE_SESSION
        assert dst == CLIENT_MAC  # L2 dest rewritten to the session MAC
        assert payload[0] == 0x11 and payload[1] == 0x00
        assert int.from_bytes(payload[2:4], "big") == SID
        plen = int.from_bytes(payload[4:6], "big")
        proto, inner = codec.parse_ppp(payload[6 : 6 + plen])
        assert proto == P.PPP_IPV4
        # inner bytes are the original IP packet
        assert inner == down[14:]

    def test_encap_then_decap_identity(self):
        by_sid, by_ip = session_tables()
        down = packets.udp_packet(AC_MAC, CLIENT_MAC, ip_to_u32("8.8.8.8"),
                                  CLIENT_IP, 53, 40000, b"z" * 21)
        pkt, ln = batch([down])
        par = parse_batch(pkt, ln)
        enc = P.pppoe_encap(pkt, ln, par.vlan_offset, par.ethertype,
                            par.dst_ip, by_ip.device_state(), TableGeom(64, 8),
                            server_mac=None)
        # upstream direction: client sends the encapped frame back
        # (swap MACs so the session-MAC check passes)
        eframe = bytearray(np.asarray(enc.out_pkt)[0][: int(enc.out_len[0])])
        eframe[0:6], eframe[6:12] = eframe[6:12], eframe[0:6]
        pkt2, ln2 = batch([bytes(eframe)])
        par2 = parse_batch(pkt2, ln2)
        dec = P.pppoe_decap(pkt2, ln2, par2.vlan_offset, par2.ethertype,
                            by_sid.device_state(), TableGeom(64, 8))
        assert bool(dec.done[0])
        out = bytes(np.asarray(dec.out_pkt)[0][: int(dec.out_len[0])])
        d = packets.decode(out)
        assert d.dst_ip == CLIENT_IP and d.payload == down[14 + 28 :]

    def test_encap_stamps_server_src_mac(self):
        """Downstream frames must carry the AC's MAC as L2 source, not the
        upstream router's (round-1 ADVICE finding)."""
        by_sid, by_ip = session_tables()
        router_mac = bytes.fromhex("02ee00000001")
        down = packets.udp_packet(router_mac, CLIENT_MAC, ip_to_u32("8.8.8.8"),
                                  CLIENT_IP, 53, 40000, b"s" * 12)
        pkt, ln = batch([down])
        par = parse_batch(pkt, ln)
        ac_hi = int.from_bytes(AC_MAC[:2], "big")
        ac_lo = int.from_bytes(AC_MAC[2:], "big")
        res = P.pppoe_encap(pkt, ln, par.vlan_offset, par.ethertype,
                            par.dst_ip, by_ip.device_state(), TableGeom(64, 8),
                            server_mac=jnp.asarray([ac_hi, ac_lo],
                                                   dtype=jnp.uint32))
        assert bool(res.done[0])
        out = bytes(np.asarray(res.out_pkt)[0][: int(res.out_len[0])])
        dst, src, et, _ = codec.parse_eth(out)
        assert dst == CLIENT_MAC and src == AC_MAC

    def test_non_pppoe_subscriber_untouched(self):
        by_sid, by_ip = session_tables()
        down = packets.udp_packet(AC_MAC, CLIENT_MAC, ip_to_u32("8.8.8.8"),
                                  ip_to_u32("10.0.0.99"), 53, 40000, b"n")
        pkt, ln = batch([down])
        par = parse_batch(pkt, ln)
        res = P.pppoe_encap(pkt, ln, par.vlan_offset, par.ethertype,
                            par.dst_ip, by_ip.device_state(), TableGeom(64, 8),
                            server_mac=None)
        assert not bool(res.done[0])
        assert int(res.out_len[0]) == len(down)
        assert bytes(np.asarray(res.out_pkt)[0][: len(down)]) == down


class TestQinQ:
    def test_push_pop_roundtrip(self):
        frame = packets.udp_packet(CLIENT_MAC, AC_MAC, CLIENT_IP,
                                   ip_to_u32("1.1.1.1"), 1111, 2222, b"qq")
        pkt, ln = batch([frame])
        s = jnp.full((pkt.shape[0],), 300, dtype=jnp.uint32)
        c = jnp.full((pkt.shape[0],), 42, dtype=jnp.uint32)
        gate = jnp.asarray([True, False, False, False])
        out, out_len, ok = P.qinq_push(pkt, ln, s, c, gate)
        assert bool(ok[0])
        tagged = bytes(np.asarray(out)[0][: int(out_len[0])])
        _, _, et, _, tags = codec.parse_eth_vlan(tagged)
        assert tags == [300, 42] and et == 0x0800

        # pop restores the original
        pkt2, ln2 = batch([tagged])
        par = parse_batch(pkt2, ln2)
        assert bool(par.is_qinq[0])
        out2, out_len2, ok2 = P.qinq_pop(pkt2, ln2, par.vlan_offset, gate)
        assert bool(ok2[0])
        restored = bytes(np.asarray(out2)[0][: int(out_len2[0])])
        assert restored == frame

    def test_single_tag_pop(self):
        frame = packets.udp_packet(CLIENT_MAC, AC_MAC, CLIENT_IP,
                                   ip_to_u32("1.1.1.1"), 1111, 2222, b"x")
        tagged = codec.eth_frame(AC_MAC, CLIENT_MAC, 0x0800, frame[14:], vlans=[77])
        pkt, ln = batch([tagged])
        par = parse_batch(pkt, ln)
        out, out_len, ok = P.qinq_pop(pkt, ln, par.vlan_offset,
                                      jnp.ones((pkt.shape[0],), dtype=bool))
        assert bool(ok[0])
        assert bytes(np.asarray(out)[0][: int(out_len[0])]) == frame


class TestControlPlaneIntegration:
    """PPPoE server negotiation -> device session tables -> device decap.

    The full slice: a CHAP session negotiated by the host stack is
    published via on_open, and the client's next DATA frame decaps on
    device (server.go:854's userspace data path moved to the TPU).
    """

    def test_negotiated_session_decaps_on_device(self):
        from bng_tpu.runtime.tables import PPPoEFastPathTables
        from tests.test_pppoe import SimClient, mkserver

        fp = PPPoEFastPathTables(nbuckets=64, stash=8)
        srv, events = mkserver()
        srv.on_open = fp.session_up
        srv.on_close = fp.session_down
        cli = SimClient(srv)
        cli.connect()
        assert fp.by_sid.count == 1 and fp.by_ip.count == 1

        # client sends session data upstream
        ip_pkt = packets.udp_packet(cli.mac, AC_MAC, cli.ip,
                                    ip_to_u32("8.8.8.8"), 5000, 53, b"dns?")[14:]
        ppp = codec.ppp_frame(P.PPP_IPV4, ip_pkt)
        pppoe = (codec.PPPoEPacket(code=0, session_id=cli.session_id, payload=ppp).encode())
        frame = codec.eth_frame(AC_MAC, cli.mac, codec.ETH_PPPOE_SESSION, pppoe)

        pkt, ln = batch([frame])
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype,
                            fp.by_sid.device_state(), fp.geom)
        assert bool(res.done[0])
        inner = bytes(np.asarray(res.out_pkt)[0][: int(res.out_len[0])])
        d = packets.decode(inner)
        assert d.src_ip == cli.ip and d.dst_port == 53

        # teardown removes the device entries
        srv.terminate(cli.session_id, __import__(
            "bng_tpu.control.pppoe.session", fromlist=["TerminateCause"]
        ).TerminateCause.ADMIN_RESET, now=2000.0)
        assert fp.by_sid.count == 0 and fp.by_ip.count == 0


class TestEnginePipelinePPPoE:
    """The PPPoE stage inside the fused Engine pipeline (runtime.engine
    pppoe=): upstream session data decaps + SNATs in one program, the
    downstream reply DNATs + re-encaps, and PPPoE control punts to the
    slow path. The reference terminates PPP in userspace per packet
    (pkg/pppoe/server.go:466-529); here only negotiation is host-side."""

    WAN_IP = ip_to_u32("8.8.8.8")
    PUB_IP = ip_to_u32("203.0.113.1")

    def _engine(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import (FastPathTables,
                                            PPPoEFastPathTables)

        fastpath = FastPathTables(sub_nbuckets=64, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=4)
        fastpath.set_server_config(AC_MAC, ip_to_u32("10.0.0.1"))
        nat = NATManager(public_ips=[self.PUB_IP], sessions_nbuckets=256,
                         sub_nat_nbuckets=64)
        pp = PPPoEFastPathTables(nbuckets=64, stash=8, server_mac=AC_MAC)
        engine = Engine(fastpath, nat, pppoe=pp, batch_size=4,
                        clock=lambda: 1000.0)

        class Sess:
            session_id = SID
            client_mac = CLIENT_MAC
            assigned_ip = CLIENT_IP

        pp.session_up(Sess())
        nat.allocate_nat(CLIENT_IP, now=1000)
        return engine, nat, pp

    def _upstream(self):
        return pppoe_data_frame()

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_upstream_decap_then_nat_fastpath(self):
        engine, nat, pp = self._engine()
        up = self._upstream()

        # packet 1: decap on device, NAT misses -> punt creates session
        r1 = engine.process([up], from_access=True)
        assert len(r1["slow"]) == 1
        assert nat.sessions.count == 1
        assert int(engine.stats.pppoe[P.PST_DECAP]) == 1

        # packet 2: decap + SNAT fully on device
        r2 = engine.process([up], from_access=True)
        assert len(r2["fwd"]) == 1
        _, out = r2["fwd"][0]
        d = packets.decode(out)
        assert d.ethertype == 0x0800  # PPPoE framing gone
        assert d.src_ip == self.PUB_IP  # SNAT applied to the inner packet
        assert d.dst_ip == self.WAN_IP

        # the NAT session key is the INNER flow (decap before NAT)
        skey = nat._key(CLIENT_IP, self.WAN_IP, 40000, 53, 17)
        assert nat.sessions.lookup(skey) is not None

    # compile-heavy (~25s: from_access=False is its own pipeline trace);
    # downstream DNAT+encap stays proven sharded by TestClusterPPPoE —
    # slow tier runs the single-engine twin
    @pytest.mark.slow
    def test_downstream_dnat_then_encap(self):
        engine, nat, pp = self._engine()
        up = self._upstream()
        engine.process([up], from_access=True)  # punt -> session
        r2 = engine.process([up], from_access=True)
        d = packets.decode(r2["fwd"][0][1])
        pub_port = d.src_port

        # reply from the WAN to the public mapping, core side
        down = packets.udp_packet(
            bytes.fromhex("02deadbeef99"), AC_MAC, self.WAN_IP,
            self.PUB_IP, 53, pub_port, b"a" * 16)
        r3 = engine.process([down], from_access=False)
        assert len(r3["fwd"]) == 1
        out = r3["fwd"][0][1]
        # outer: PPPoE session framing to the subscriber MAC, from AC MAC
        assert out[0:6] == CLIENT_MAC and out[6:12] == AC_MAC
        assert int.from_bytes(out[12:14], "big") == codec.ETH_PPPOE_SESSION
        pkt6 = codec.PPPoEPacket.decode(out[14:])
        assert pkt6.session_id == SID
        proto, inner = codec.parse_ppp(pkt6.payload)
        assert proto == P.PPP_IPV4
        # inner: DNAT back to the subscriber private IP
        din = packets.decode(b"\x00" * 12 + b"\x08\x00" + inner)
        assert din.dst_ip == CLIENT_IP
        assert din.src_ip == self.WAN_IP
        assert int(engine.stats.pppoe[P.PST_ENCAP]) == 1

    def test_pppoe_control_punts_to_slow_path(self):
        got = []

        def slow(frame):
            got.append(frame)
            return None

        engine, nat, pp = self._engine()
        engine.slow_path = slow
        padi = codec.eth_frame(
            b"\xff" * 6, CLIENT_MAC, codec.ETH_PPPOE_DISCOVERY,
            codec.PPPoEPacket(code=codec.CODE_PADI, session_id=0,
                              payload=b"").encode())
        r = engine.process([padi], from_access=True)
        assert len(r["slow"]) == 1
        assert got and got[0] == padi

    def test_unknown_session_data_passes(self):
        engine, nat, pp = self._engine()
        frame = pppoe_data_frame(sid=0x999)
        r = engine.process([frame], from_access=True)
        assert len(r["slow"]) == 1 and not r["fwd"]
