"""Wire pump (ISSUE 15): the batch-native vector pump against the
scalar per-frame oracle, plus the wire serving proof.

Three layers, PR-14 discipline throughout:

1. **Bit-identity corpus** — the vector pump (native batch verbs,
   headroom-aware descriptors) must be indistinguishable from the
   scalar per-frame loop over every edge case: partial fill, a full
   kernel fill ring, TX stall + retry, headroom offsets (including 0),
   forged RX lengths, an rx-full ring. Identity covers moved-frame
   order, verdict routing, egress bytes, pump_stats AND ring stats.
2. **Satellite pins** — the frame-accounting leak fix (a failed submit
   must return its UMEM frame or the fill pool drains permanently) and
   the explicit `_tx_pending` bound with counted overflow drops.
3. **Wire serving** — the memory-rung twin of the veth proof: DORA +
   NAT new-flow punt + QoS drop + PPPoE session data through
   `Engine.process_ring_pipelined` over the full kernel-rings -> pump
   -> UMEM ring -> engine -> pump loop, far-end replies byte-exact
   across both pump implementations. The live AF_XDP copy-mode rung on
   veth runs the same four scenarios when privileges allow (slow tier).
"""

import time

import numpy as np
import pytest

from bng_tpu.chaos import faults
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.runtime import xsk
from bng_tpu.runtime.ring import NativeRing, load_native
from bng_tpu.utils.net import ip_to_u32

pytestmark = pytest.mark.wire

needs_native = pytest.mark.skipif(load_native() is None,
                                  reason="no C++ toolchain")

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")
T0 = 1_753_000_000


# ---------------------------------------------------------------------------
# corpus harness: one scripted scenario, executed on both pump paths
# ---------------------------------------------------------------------------

def _mk(path, *, nframes=64, frame_size=512, depth=32, headroom=128,
        ring_size=32, tx_room=None, tx_pending_cap=4096):
    ring = NativeRing(nframes=nframes, frame_size=frame_size, depth=depth)
    kern = xsk.SimKernelRings(ring, headroom=headroom, ring_size=ring_size,
                              tx_room=tx_room)
    pump = xsk.WirePump(ring, kern, path=path,
                        tx_pending_cap=tx_pending_cap)
    return ring, kern, pump


def _discover(i):
    mac = (0x02C0FFEE0000 + i).to_bytes(6, "big")
    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x1000 + i)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _data(i, size=96):
    return packets.udp_packet(
        b"\x02" * 6, b"\x04" * 6, 0x0A000000 + i, 0x08080808,
        1024 + i, 443, bytes([i % 256]) * size)


def _mixed(n, seed=0):
    """DHCP control + UDP data interleaved: the classify/steer path on
    submit must route identically on both pumps."""
    return [(_discover(seed + i) if i % 3 == 0 else _data(seed + i))
            for i in range(n)]


def _reflect(ring, budget=32, slot=512, pattern=(2,)):
    """Host-only ring consumer: assemble, stamp verdicts from `pattern`
    cycled by lane (2=TX 3=FWD 1=DROP 0=PASS), complete. Returns the
    assembled (bytes, flags, verdict) rows — frame ORDER is part of the
    identity contract."""
    out = np.zeros((budget, slot), dtype=np.uint8)
    ln = np.zeros(budget, dtype=np.uint32)
    fl = np.zeros(budget, dtype=np.uint32)
    n = ring.assemble(out, ln, fl)
    rows = []
    if n:
        verdict = np.array([pattern[i % len(pattern)] for i in range(n)],
                           dtype=np.uint8)
        ring.complete(verdict, out[:n], ln[:n], n)
        rows = [(bytes(out[i, :ln[i]]), int(fl[i]), int(verdict[i]))
                for i in range(n)]
        # PASS lanes land on the slow ring, outside the pump's loop —
        # drain them so frame accounting closes
        while ring.slow_pop() is not None:
            pass
    return rows


def _run(path, cfg, script):
    """Execute `script` ops against a fresh (ring, kernel, pump) stack
    and trace EVERYTHING observable."""
    ring, kern, pump = _mk(path, **cfg)
    trace = []
    for op in script:
        kind = op[0]
        if kind == "inject":
            kern.inject_many(op[1])
        elif kind == "inject_claim":
            kern.inject(op[1], claim_len=op[2])
        elif kind == "pump":
            trace.append(("moved", pump.pump(budget=op[1])))
        elif kind == "deliver":
            kern.deliver()
        elif kind == "reflect":
            trace.append(("rows", _reflect(ring, pattern=op[1])))
        elif kind == "drain":
            trace.append(("egress", kern.drain_egress()))
        else:  # pragma: no cover - script typo guard
            raise AssertionError(kind)
    trace.append(("stats", dict(pump.pump_stats)))
    trace.append(("ring", ring.stats()))
    trace.append(("free", ring.free_frames()))
    trace.append(("pending", pump.tx_pending()))
    last = pump.last_path
    ring.close()
    return trace, last


def _round(n=8, budget=16, pattern=(2, 3, 1), seed=0):
    """One full wire round: inject -> pump (rx) -> reflect -> pump (tx)
    -> drain."""
    return [("inject", _mixed(n, seed=seed)), ("pump", budget),
            ("deliver",), ("pump", budget), ("reflect", pattern),
            ("pump", budget), ("drain",)]


CORPUS = {
    "steady_state": (
        {},
        _round(8, seed=0) + _round(8, seed=8) + _round(8, seed=16)),
    "partial_fill": (
        {},
        _round(3, budget=16, seed=0) + _round(1, budget=16, seed=3)
        + _round(0, budget=16, seed=4)),
    "full_fill_ring": (
        # kernel rings far smaller than the budget: fill pushes must
        # come back partial and the pump must hand the excess frames
        # straight back to the pool
        {"ring_size": 8, "nframes": 64},
        _round(6, budget=32, seed=0) + _round(6, budget=32, seed=6)),
    "tx_stall_retry": (
        # kernel TX accepts 3/round: pending descriptors must retry in
        # order across rounds on both paths
        {"tx_room": 3},
        _round(6, pattern=(2,), seed=0) + _round(6, pattern=(2,), seed=6)
        + _round(0, pattern=(2,), seed=12)),
    "headroom_zero": (
        {"headroom": 0},
        _round(8, seed=0) + _round(8, seed=8)),
    "headroom_deep": (
        # frame_size 512, headroom 256: room is 256 bytes — the
        # copy-mode shape at its tightest
        {"headroom": 256},
        _round(6, seed=0) + _round(6, seed=6)),
    "forged_rx_len": (
        # kernel-misbehavior guard: a claimed length that cannot fit
        # the chunk room (512-128=384) must drop AND recycle; the
        # boundary length (exactly 384) must pass
        {},
        [("inject", _mixed(2, seed=0)),
         ("inject_claim", b"z" * 64, 500),
         ("inject_claim", b"y" * 64, 384),
         ("inject_claim", b"x" * 64, 385),
         ("pump", 16), ("deliver",), ("pump", 16),
         ("reflect", (2,)), ("pump", 16), ("drain",)]),
    "rx_ring_full": (
        # ring rx queue depth 8 < injected 14: the overflow submits
        # must fail rx-full and recycle on both paths
        {"depth": 8, "ring_size": 32},
        [("inject", _mixed(14, seed=0)), ("pump", 16), ("deliver",),
         ("pump", 16), ("reflect", (2,)), ("pump", 16), ("drain",)]
        + _round(4, seed=20)),
}


@needs_native
class TestBitIdentityCorpus:
    """vector == scalar over every edge case: same assembled frame
    order+flags, same verdict routing, same egress bytes, same
    pump_stats, same ring stats, same frame accounting."""

    @pytest.mark.parametrize("name", sorted(CORPUS))
    def test_identity(self, name):
        cfg, script = CORPUS[name]
        scalar, last_s = _run("scalar", cfg, script)
        vector, last_v = _run("vector", cfg, script)
        assert last_s == "scalar"
        assert last_v == "vector", "vector cohort silently ran scalar"
        for (ks, vs), (kv, vv) in zip(scalar, vector):
            assert (ks, vs) == (kv, vv), (
                f"{name}: first divergence at {ks!r}:\n"
                f"  scalar: {vs!r}\n  vector: {vv!r}")
        assert scalar == vector

    def test_corpus_actually_exercises_the_edges(self):
        """The corpus must HIT the paths it claims to pin (an edge case
        that never fires pins nothing)."""
        cfg, script = CORPUS["forged_rx_len"]
        trace, _ = _run("vector", cfg, script)
        stats = dict(trace)["stats"]
        assert stats["rx_submit_fail"] == 2  # 500 and 385, not 384
        cfg, script = CORPUS["rx_ring_full"]
        trace, _ = _run("vector", cfg, script)
        assert dict(trace)["ring"]["rx_full"] >= 1
        cfg, script = CORPUS["tx_stall_retry"]
        trace, _ = _run("vector", cfg, script)
        stats = dict(trace)["stats"]
        assert stats["tx"] == 12  # every stalled descriptor retried out
        cfg, script = CORPUS["full_fill_ring"]
        trace, _ = _run("vector", cfg, script)
        assert dict(trace)["free"] > 0


@needs_native
class TestFrameAccounting:
    """The ISSUE-15 satellite pins."""

    @pytest.mark.parametrize("path", ["scalar", "vector"])
    def test_forged_len_storm_does_not_drain_the_pool(self, path):
        """The leak fix: a dropped RX frame must return to the fill
        pool. Pre-fix, each forged-length frame leaked one UMEM frame —
        16 frames of pressure on a 16-frame pool drained it permanently.
        Post-fix the pump survives indefinitely and still serves."""
        ring, kern, pump = _mk(path, nframes=16, ring_size=16)
        for i in range(50):  # >> nframes: pre-fix this wedges at i=16
            kern.inject(b"q" * 64, claim_len=500)
            pump.pump(budget=8)
            kern.deliver()
        assert pump.pump_stats["rx_submit_fail"] == 50
        # the pool is whole: a good frame still traverses end to end
        good = _data(7)
        kern.inject(good)
        pump.pump(budget=8)
        kern.deliver()
        pump.pump(budget=8)
        rows = _reflect(ring)
        assert [r[0] for r in rows] == [good]
        pump.pump(budget=8)
        assert kern.drain_egress() == [good]
        ring.close()

    @pytest.mark.parametrize("path", ["scalar", "vector"])
    def test_garbage_rx_addr_dropped_identically(self, path):
        """Kernel-misbehavior guard, address edition: an RX descriptor
        whose address lies OUTSIDE the UMEM must be dropped without
        touching memory (pre-fix the scalar path memmove'd from/to the
        forged address — out-of-bounds write) and without recycling a
        frame that was never ours, counted as rx_submit_fail + the
        ring's bad_desc on BOTH paths."""
        ring, kern, pump = _mk(path)
        bad = np.zeros(1, dtype=np.uint64)
        badl = np.zeros(1, dtype=np.uint32)
        bad[0] = ring.umem_size + 4096  # forged: past the UMEM end
        badl[0] = 64
        kern._rx_a.push(bad, 1)  # white-box: forge the raw descriptor
        kern._rx_l.push(badl, 1)
        free_before = ring.free_frames()
        pump.pump(budget=8)
        assert pump.pump_stats["rx_submit_fail"] == 1
        assert ring.stats()["bad_desc"] == 1
        # pool accounting exact: the fill phase took its frames, and the
        # forged address neither leaked one nor recycled one that was
        # never ours
        assert ring.free_frames() == free_before - pump.pump_stats["filled"]
        # the stack still serves: a good frame round-trips
        good = _data(9)
        kern.inject(good)
        pump.pump(budget=8)
        kern.deliver()
        pump.pump(budget=8)
        rows = _reflect(ring)
        assert [r[0] for r in rows] == [good]
        ring.close()

    @pytest.mark.parametrize("path", ["scalar", "vector"])
    def test_tx_pending_bounded_and_overflow_counted(self, path):
        """The pending-TX queue is explicitly bounded: a stalled kernel
        TX ring drops (and counts, and recycles) beyond the cap instead
        of growing without limit."""
        ring, kern, pump = _mk(path, tx_room=0, tx_pending_cap=4)
        sent = []
        for rnd in range(3):
            frames = [_data(rnd * 8 + i) for i in range(8)]
            sent.append(frames)
            kern.inject_many(frames)
            pump.pump(budget=16)
            kern.deliver()
            pump.pump(budget=16)
            _reflect(ring, pattern=(2,))
            pump.pump(budget=16)
            assert pump.tx_pending() <= 4
        assert pump.pump_stats["tx_overflow"] == 3 * 8 - 4
        assert pump.pump_stats["tx"] == 0
        # dropped frames were recycled, not leaked: un-stall and the 4
        # RETAINED (oldest) descriptors egress, then serving continues
        kern.tx_room = None
        pump.pump(budget=16)
        assert kern.drain_egress() == sent[0][:4]
        assert pump.tx_pending() == 0
        good = _data(99)
        kern.inject(good)
        pump.pump(budget=16)
        kern.deliver()
        pump.pump(budget=16)
        _reflect(ring, pattern=(2,))
        pump.pump(budget=16)
        assert kern.drain_egress() == [good]
        ring.close()

    def test_chaos_armed_rounds_take_the_scalar_path(self):
        """Fault-point hit accounting is per-call: an armed plan forces
        the scalar oracle (the PR-14 fleet/admission mold), and the
        selection is re-evaluated every round."""
        ring, kern, pump = _mk("vector")
        kern.inject_many(_mixed(4))
        pump.pump(budget=8)
        assert pump.last_path == "vector"
        with faults.armed(faults.FaultPlan(seed=1), log=False):
            pump.pump(budget=8)
            assert pump.last_path == "scalar"
        pump.pump(budget=8)
        assert pump.last_path == "vector"
        assert pump.path == "vector"  # construction identity unchanged
        ring.close()


class TestSelectorAndLedger:
    def test_env_selector_validates(self, monkeypatch):
        monkeypatch.setattr(xsk, "WIRE_PUMP", "bogus")
        with pytest.raises(ValueError, match="BNG_WIRE_PUMP"):
            xsk.resolved_wire_pump()
        # the fingerprint label must never raise (ledger best-effort)
        assert xsk.current_wire_pump_label() == "bogus"

    @needs_native
    def test_explicit_bad_path_refused(self):
        ring = NativeRing(nframes=16, frame_size=256, depth=8)
        kern = xsk.SimKernelRings(ring, ring_size=8)
        with pytest.raises(ValueError, match="unknown wire pump"):
            xsk.WirePump(ring, kern, path="turbo")
        ring.close()

    def test_ledger_cohort_identity(self):
        """wire_pump joins the cohort key: legacy lines default scalar,
        and a cross-path trend refuses with rc=3 naming both paths."""
        from bng_tpu.telemetry import ledger

        def line(i, wp=None, v=100.0):
            ln = {"schema_version": 1, "run_id": f"r{i}",
                  "ts": "2026-08-04T00:00:00",
                  "metric": "wire pump p50 (wire_rx+wire_tx)",
                  "value": v, "unit": "us", "vs_baseline": 1.0,
                  "env": {"platform": "cpu", "device_kind": "cpu"}}
            if wp:
                ln["wire_pump"] = wp
            return ln

        assert ledger.wire_pump(line(0)) == "scalar"  # legacy default
        assert ledger.wire_pump(line(0, wp="vector")) == "vector"
        env_line = line(0)
        env_line["env"]["wire_pump"] = "vector"
        assert ledger.wire_pump(env_line) == "vector"
        assert ledger.cohort_key(line(0)) != ledger.cohort_key(
            line(0, wp="vector"))

        hist = [line(i) for i in range(4)]  # legacy scalar history
        rep = ledger.gate(hist + [line(9, wp="vector", v=10.0)])
        assert rep.rc == 3
        joined = " ".join(rep.notes)
        assert "wire='vector'" in joined and "wire=scalar" in joined
        # same-path trend still gates normally
        rep2 = ledger.gate(
            [line(i, wp="vector") for i in range(4)]
            + [line(9, wp="vector", v=101.0)])
        assert rep2.rc == 0


class TestWireTelemetry:
    def test_wire_stages_in_the_fixed_vocabulary(self):
        from bng_tpu.telemetry import spans as tele
        from bng_tpu.telemetry.slo import DEFAULT_SLOS

        assert "wire_rx" in tele.STAGE_NAMES
        assert "wire_tx" in tele.STAGE_NAMES
        budgeted = {s.stage for s in DEFAULT_SLOS}
        assert {"wire_rx", "wire_tx"} <= budgeted

    @needs_native
    def test_pump_laps_the_wire_stages(self):
        from bng_tpu.telemetry import FlightRecorder, RecorderConfig
        from bng_tpu.telemetry import spans as tele

        ring, kern, pump = _mk("vector")
        tr = tele.Tracer(recorder=FlightRecorder(RecorderConfig()))
        tele.arm(tr)
        try:
            kern.inject_many(_mixed(4))
            pump.pump(budget=8)
            kern.deliver()
            pump.pump(budget=8)
        finally:
            tele.disarm()
        bd = tr.breakdown()
        assert bd["wire_rx"]["count"] == 2
        assert bd["wire_tx"]["count"] == 2
        ring.close()

    def test_wire_fallback_trigger_dumps_flight_ring(self, tmp_path):
        from bng_tpu.telemetry import FlightRecorder, RecorderConfig
        from bng_tpu.telemetry import recorder as rec_mod
        from bng_tpu.telemetry import spans as tele

        rec = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        tele.arm(tele.Tracer(recorder=rec))
        try:
            path = tele.trigger(rec_mod.TRIG_WIRE_FALLBACK,
                                "requested 'eth9' landed on memory")
        finally:
            tele.disarm()
        assert path and rec.triggers.get(rec_mod.TRIG_WIRE_FALLBACK) == 1

    @needs_native
    def test_collect_wire_metrics(self):
        from bng_tpu.control.metrics import BNGMetrics

        ring, kern, pump = _mk("vector")
        kern.inject_many(_mixed(4))
        pump.pump(budget=8)
        kern.deliver()
        pump.pump(budget=8)
        att = xsk.WireAttachment(xsk.MODE_MEMORY, None, "no iface")
        m = BNGMetrics()
        m.collect_wire(att, pump=pump)
        text = m.registry.expose()
        assert 'bng_wire_rung{mode="memory"} 1' in text
        assert 'bng_wire_rung{mode="zerocopy"} 0' in text
        assert 'bng_wire_pump_path{path="vector"} 1' in text
        assert 'bng_wire_frames_total{dir="rx"} 4' in text
        assert "bng_wire_filled_total" in text
        assert "bng_wire_tx_overflow_total 0" in text
        assert "bng_wire_tx_pending 0" in text
        ring.close()


class TestWireLoopTargetXid:
    """The loadtest wire target matches replies to request lanes by
    BOOTP xid — the wire hands back frames, not lane indexes."""

    def test_request_reply_and_vlan_tolerance(self):
        from bng_tpu.loadtest import WireLoopTarget

        mac = bytes.fromhex("02c0ffee0030")
        req = _dhcp(mac, dhcp_codec.DISCOVER, xid=0xABCD1234)
        assert WireLoopTarget._xid(req, reply=False) == 0xABCD1234
        assert WireLoopTarget._xid(req, reply=True) is None  # op=1
        # single VLAN tag between L2 and the IP header
        tagged = req[:12] + b"\x81\x00\x00\x64" + req[12:]
        assert WireLoopTarget._xid(tagged, reply=False) == 0xABCD1234
        assert WireLoopTarget._xid(b"\x00" * 13, reply=False) is None
        assert WireLoopTarget._xid(_data(0), reply=False) is None


# ---------------------------------------------------------------------------
# wire serving: the four-scenario proof (memory-rung twin, tier-1)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=T0):
        self.t = float(t)

    def __call__(self):
        return self.t


class _Sess:
    session_id = 0x0042
    client_mac = bytes.fromhex("02c0ffee0101")
    assigned_ip = ip_to_u32("10.0.0.50")


def _serving_stack():
    """The full production stack of the veth proof, memory-rung twin:
    DHCP + NAT + QoS + PPPoE behind one Engine."""
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.nat import NATManager
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime.engine import Engine, QoSTables
    from bng_tpu.runtime.tables import FastPathTables, PPPoEFastPathTables

    clock = _Clock()
    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=24, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    qos = QoSTables(nbuckets=256)
    pp = PPPoEFastPathTables(nbuckets=64, stash=8, server_mac=SERVER_MAC)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                        fastpath_tables=fastpath, clock=clock)
    engine = Engine(fastpath, nat, qos, pppoe=pp, batch_size=8,
                    slow_path=server.handle_frame, clock=clock)
    pp.session_up(_Sess())
    nat.allocate_nat(_Sess.assigned_ip, T0)
    nat.allocate_nat(ip_to_u32("10.0.0.55"), T0)
    nat.allocate_nat(ip_to_u32("10.0.0.60"), T0)
    qos.set_subscriber(ip_to_u32("10.0.0.60"), down_bps=8000, up_bps=8000,
                       up_burst=1500, down_burst=1500)
    return engine, server, nat, qos


def _pppoe_data(sport=40000):
    from bng_tpu.control.pppoe import codec
    from bng_tpu.ops import pppoe as P

    inner = packets.udp_packet(_Sess.client_mac, SERVER_MAC,
                               _Sess.assigned_ip, ip_to_u32("8.8.8.8"),
                               sport, 53, b"q" * 32)[14:]
    ppp = codec.ppp_frame(P.PPP_IPV4, inner)
    pppoe = codec.PPPoEPacket(code=0, session_id=_Sess.session_id,
                              payload=ppp).encode()
    return codec.eth_frame(SERVER_MAC, _Sess.client_mac,
                           codec.ETH_PPPOE_SESSION, pppoe)


def _qos_frame():
    """One 442-byte frame of the shaped subscriber's established flow
    (10.0.0.60 -> 8.8.8.8:9999, 1500-byte token bucket)."""
    return packets.udp_packet(bytes.fromhex("02c0ffee0020"), SERVER_MAC,
                              ip_to_u32("10.0.0.60"), ip_to_u32("8.8.8.8"),
                              1111, 9999, b"x" * 400)


def _dhcp(mac, msg_type, xid, **kw):
    p = dhcp_codec.build_request(mac, msg_type, xid=xid, **kw)
    p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                      bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(320, b"\x00"))


def _drive_wire_scenarios(engine, ring, kern, pump):
    """Run the four acceptance scenarios through the FULL wire loop
    (far-end inject -> kernel rings -> pump -> UMEM ring -> engine ->
    pump -> far-end drain). Returns {scenario: [egress frames]}."""

    def roundtrip(frames, rounds=6):
        kern.inject_many(frames)
        got = []
        for _ in range(rounds):
            pump.pump(budget=16)
            kern.deliver()
            engine.process_ring_pipelined(ring)
            engine.flush_pipeline(ring)
            pump.pump(budget=16)
            got.extend(kern.drain_egress())
        return got

    out = {}
    mac = bytes.fromhex("02c0ffee0001")
    # 1. DORA: DISCOVER #1 -> slow-path OFFER; REQUEST -> ACK (lease
    #    installed); DISCOVER #2 -> answered on device
    offers = roundtrip([_dhcp(mac, dhcp_codec.DISCOVER, xid=0x11)])
    assert len(offers) == 1, "no OFFER egressed the wire"
    offer = dhcp_codec.decode(packets.decode(offers[0]).payload)
    assert offer.msg_type == dhcp_codec.OFFER
    acks = roundtrip([_dhcp(mac, dhcp_codec.REQUEST, xid=0x12,
                            requested_ip=offer.yiaddr,
                            server_id=SERVER_IP)])
    assert dhcp_codec.decode(
        packets.decode(acks[0]).payload).msg_type == dhcp_codec.ACK
    tx_before = engine.stats.tx
    offers2 = roundtrip([_dhcp(mac, dhcp_codec.DISCOVER, xid=0x13)])
    assert engine.stats.tx == tx_before + 1  # on-device, not slow path
    out["dora"] = offers + acks + offers2

    # 2. NAT: packet 1 punts (no egress), packet 2 SNATs on device
    sub_ip = ip_to_u32("10.0.0.55")
    f = packets.udp_packet(bytes.fromhex("02c0ffee0010"), SERVER_MAC,
                           sub_ip, ip_to_u32("93.184.216.34"), 40000, 443,
                           b"nat-payload")
    punted = roundtrip([f])
    assert punted == [], "new-flow punt must not egress"
    natted = roundtrip([f])
    assert len(natted) == 1
    d = packets.decode(natted[0])
    assert d.src_ip == ip_to_u32("203.0.113.1")  # SNAT applied
    out["nat"] = natted

    # 3. QoS: an ESTABLISHED flow (punt first, then device SNAT+shape):
    #    the 1500-byte bucket passes some ~442-byte frames to the wire
    #    and the over-budget drops never egress
    assert roundtrip([_qos_frame()]) == []  # punt creates the session
    dropped_before = engine.stats.dropped
    shaped = roundtrip([_qos_frame() for _ in range(4)])
    n_dropped = engine.stats.dropped - dropped_before
    assert n_dropped >= 1, "QoS never dropped"
    assert len(shaped) == 4 - n_dropped >= 1
    out["qos"] = shaped

    # 4. PPPoE: data frame 1 punts (inner-flow NAT miss), frame 2
    #    decaps + SNATs on device
    up = _pppoe_data()
    assert roundtrip([up]) == []
    fwd = roundtrip([up])
    assert len(fwd) == 1
    d = packets.decode(fwd[0])
    assert d.ethertype == 0x0800  # PPPoE framing stripped on device
    assert d.src_ip == ip_to_u32("203.0.113.1")
    out["pppoe"] = fwd
    return out


@needs_native
class TestWireServingMemoryRung:
    """The acceptance twin: the four scenarios over the memory rung,
    byte-exact across BOTH pump implementations (identical stacks,
    identical traffic, frozen clocks — any wire-visible divergence
    between the pumps is a bug)."""

    def test_four_scenarios_byte_exact_across_pumps(self):
        results = {}
        for path in ("scalar", "vector"):
            engine, server, nat, qos = _serving_stack()
            ring = NativeRing(nframes=256, frame_size=2048, depth=64)
            kern = xsk.SimKernelRings(ring, headroom=256, ring_size=128)
            pump = xsk.WirePump(ring, kern, path=path)
            results[path] = _drive_wire_scenarios(engine, ring, kern, pump)
            assert pump.last_path == path
            assert pump.pump_stats["rx_submit_fail"] == 0
            assert pump.pump_stats["tx_overflow"] == 0
            ring.close()
        assert results["scalar"] == results["vector"], (
            "far-end bytes diverge between pump implementations")


# ---------------------------------------------------------------------------
# wire serving: the live AF_XDP copy-mode rung on veth (slow tier)
# ---------------------------------------------------------------------------

def _veth_ok() -> bool:
    import subprocess

    r = subprocess.run(["ip", "link", "add", "bngwp0", "type", "veth",
                        "peer", "name", "bngwp1"], capture_output=True)
    if r.returncode != 0:
        return False
    subprocess.run(["ip", "link", "del", "bngwp0"], capture_output=True)
    return True


def _live_rung_possible() -> bool:
    from bng_tpu.runtime import xdp_redirect

    return (xsk.probe() != "unavailable" and xsk.probe() != xsk.MODE_MEMORY
            and xdp_redirect.probe() and _veth_ok())


@pytest.mark.slow  # heavy e2e: the 870s tier-1 cap (ISSUE 15 satellite)
@pytest.mark.skipif(not _live_rung_possible(),
                    reason="needs CAP_NET_ADMIN + AF_XDP + CAP_BPF")
class TestWireServingVeth:
    """The four scenarios over the REAL kernel: AF_XDP copy-mode bind
    on a veth pair, frames injected on the far peer with AF_PACKET,
    replies asserted byte-exact against the memory-rung twin's output
    (the twin ran the identical stack, so any difference is the wire)."""

    IF_A, IF_B = "bngwp0", "bngwp1"

    @pytest.fixture
    def veth(self):
        import subprocess

        subprocess.run(["ip", "link", "del", self.IF_A], capture_output=True)
        subprocess.run(["ip", "link", "add", self.IF_A, "type", "veth",
                        "peer", "name", self.IF_B], check=True,
                       capture_output=True)
        for i in (self.IF_A, self.IF_B):
            subprocess.run(["ip", "link", "set", i, "up"], check=True,
                           capture_output=True)
        time.sleep(0.3)
        yield
        subprocess.run(["ip", "link", "del", self.IF_A], capture_output=True)

    @pytest.mark.parametrize("pump_path", ["scalar", "vector"])
    def test_four_scenarios_live(self, veth, pump_path):
        import socket as so

        from bng_tpu.runtime import xdp_redirect

        # reference: the memory-rung twin over an identical stack gives
        # the exact reply bytes the live rung must reproduce
        engine_ref, _, _, _ = _serving_stack()
        ring_ref = NativeRing(nframes=256, frame_size=2048, depth=64)
        kern_ref = xsk.SimKernelRings(ring_ref, headroom=256, ring_size=128)
        expected = _drive_wire_scenarios(
            engine_ref, ring_ref, kern_ref,
            xsk.WirePump(ring_ref, kern_ref, path=pump_path))
        ring_ref.close()

        engine, server, nat, qos = _serving_stack()
        ring = NativeRing(nframes=4096, frame_size=2048, depth=1024)
        att = xsk.open_wire(ring, ifname=self.IF_A, queue=0,
                            pump_path=pump_path)
        assert att.mode == xsk.MODE_COPY, (att.mode, att.detail)
        s = att.xsk
        redir = xdp_redirect.XdpRedirect(self.IF_A, {0: s.fd})
        txs = so.socket(so.AF_PACKET, so.SOCK_RAW)
        txs.bind((self.IF_B, 0))
        rxs = so.socket(so.AF_PACKET, so.SOCK_RAW, so.htons(0x0003))
        rxs.bind((self.IF_B, 0))
        rxs.setblocking(False)
        try:
            s.pump()  # pre-stock the kernel fill ring

            def exchange(frames, want: int, deadline_s=8.0):
                for f in frames:
                    txs.send(f)
                got = []
                deadline = time.time() + deadline_s
                while time.time() < deadline and len(got) < want:
                    s.pump(budget=64)
                    engine.process_ring_pipelined(ring)
                    engine.flush_pipeline(ring)
                    s.pump(budget=64)
                    while True:
                        try:
                            got.append(rxs.recv(4096))
                        except (BlockingIOError, OSError):
                            break
                    time.sleep(0.01)
                return got

            mac = bytes.fromhex("02c0ffee0001")
            # 1. DORA, byte-exact vs the twin
            got = exchange([_dhcp(mac, dhcp_codec.DISCOVER, xid=0x11)], 1)
            assert expected["dora"][0] in got
            offer = dhcp_codec.decode(
                packets.decode(expected["dora"][0]).payload)
            got = exchange([_dhcp(mac, dhcp_codec.REQUEST, xid=0x12,
                                  requested_ip=offer.yiaddr,
                                  server_id=SERVER_IP)], 1)
            assert expected["dora"][1] in got
            tx_before = engine.stats.tx
            got = exchange([_dhcp(mac, dhcp_codec.DISCOVER, xid=0x13)], 1)
            assert expected["dora"][2] in got
            assert engine.stats.tx == tx_before + 1  # on-device OFFER

            # 2. NAT new-flow punt, then device SNAT
            sub_ip = ip_to_u32("10.0.0.55")
            f = packets.udp_packet(bytes.fromhex("02c0ffee0010"),
                                   SERVER_MAC, sub_ip,
                                   ip_to_u32("93.184.216.34"), 40000, 443,
                                   b"nat-payload")
            got = exchange([f], 1, deadline_s=2.0)  # punt: nothing OURS
            assert expected["nat"][0] not in got
            got = exchange([f], 1)
            assert expected["nat"][0] in got

            # 3. QoS: the over-budget frames drop, survivors byte-exact
            exchange([_qos_frame()], 1, deadline_s=2.0)  # punt
            dropped_before = engine.stats.dropped
            got = exchange([_qos_frame() for _ in range(4)],
                           len(expected["qos"]))
            assert engine.stats.dropped > dropped_before
            for surviving in expected["qos"]:
                assert surviving in got

            # 4. PPPoE session data: punt, then decap+SNAT on device
            up = _pppoe_data()
            exchange([up], 1, deadline_s=2.0)
            got = exchange([up], 1)
            assert expected["pppoe"][0] in got

            assert s.pump_stats["rx"] > 0 and s.pump_stats["tx"] > 0
            assert s.wire_pump.last_path == pump_path
        finally:
            txs.close()
            rxs.close()
            redir.close()
            s.close()
            ring.close()
