"""HA pair + resilience tests.

Mirrors the reference strategy: HA active+standby in one process
(pkg/ha/sync_test.go), controllable health checkers driving the
partition state machine (pkg/resilience/partition_test.go:15-50).
"""

from bng_tpu.control.ha import (
    ActiveSyncer,
    FailoverController,
    FailoverState,
    HealthMonitor,
    HealthState,
    InMemorySessionStore,
    Role,
    SessionState,
    StandbySyncer,
)
from bng_tpu.control.resilience import (
    CachedProfile,
    ConflictDetector,
    DegradedRADIUSHandler,
    PartitionState,
    PoolLevel,
    PoolMonitor,
    RequestQueue,
    ResilienceManager,
)


def sess(i, at=0.0):
    return SessionState(session_id=f"s{i}", mac=f"02:00:00:00:00:{i:02x}",
                        ip=0x0A000000 + i, updated_at=at)


class TestHASync:
    def _pair(self):
        active = ActiveSyncer(InMemorySessionStore())
        up = {"ok": True}

        def transport():
            if not up["ok"]:
                raise ConnectionError("active down")
            return active

        standby = StandbySyncer(InMemorySessionStore(), transport)
        return active, standby, up

    def test_full_sync_then_deltas(self):
        active, standby, _ = self._pair()
        for i in range(5):
            active.push_change(sess(i))
        standby.tick(0.0)
        assert standby.connected
        assert len(standby.store) == 5
        assert standby.stats["full_syncs"] == 1
        # live delta
        active.push_change(sess(9))
        assert standby.store.get("s9") is not None
        active.push_change(None, session_id="s0")
        assert standby.store.get("s0") is None
        assert len(standby.store) == 5

    def test_reconnect_with_backoff_and_replay(self):
        active, standby, up = self._pair()
        active.push_change(sess(1))
        standby.tick(0.0)
        assert standby.connected

        # active "dies": standby disconnects, retries with backoff
        up["ok"] = False
        standby.disconnect()
        standby.tick(1.0)
        assert not standby.connected
        # backoff: next attempt not before 1+1s
        up["ok"] = True
        active.push_change(sess(2))  # happens while disconnected
        standby.tick(1.5)
        assert not standby.connected  # still backing off
        standby.tick(2.5)
        assert standby.connected
        # missed change arrived via replay, not full resync
        assert standby.store.get("s2") is not None
        assert standby.stats["full_syncs"] == 1

    def test_replay_gap_forces_full_sync(self):
        active, standby, up = self._pair()
        active.push_change(sess(1))
        standby.tick(0.0)
        standby.disconnect()
        # overflow the replay buffer while disconnected
        active._replay_cap = 4
        for i in range(10, 20):
            active.push_change(sess(i))
        standby.tick(10.0)
        assert standby.connected
        assert standby.stats["full_syncs"] == 2
        assert len(standby.store) == 11

    def test_replay_exact_wrap_boundary(self):
        """The off-by-one that silently loses sessions: a standby whose
        seq+1 is the OLDEST buffered change replays completely; a standby
        whose seq+1 just fell off must get None (full resync), never a
        truncated list that skips the evicted change."""
        active = ActiveSyncer(InMemorySessionStore(), replay_buffer=4)
        for i in range(1, 11):  # seqs 1..10; buffer holds 7,8,9,10
            active.push_change(sess(i))
        # seq=6: successor (7) is the oldest buffered change -> complete
        replay = active.replay_since(6)
        assert replay is not None
        assert [c.seq for c in replay] == [7, 8, 9, 10]
        # seq=5: successor (6) was evicted -> None, NOT [7..10]
        assert active.replay_since(5) is None
        # fully caught up -> empty delta, not a resync signal
        assert active.replay_since(10) == []

    def test_incremental_replay_resumes_after_wrap_resync(self):
        """After a wrap forces a full resync, the standby's next
        reconnect gap (within the buffer) must ride replay again."""
        active, standby, up = self._pair()
        active._replay_cap = 4
        active.push_change(sess(1))
        standby.tick(0.0)
        standby.disconnect()
        for i in range(10, 20):  # wrap the buffer while away
            active.push_change(sess(i))
        standby.tick(10.0)
        assert standby.stats["full_syncs"] == 2  # wrap -> resync
        # disconnect again; miss a SMALL number of changes (< cap)
        standby.disconnect()
        active.push_change(sess(30))
        active.push_change(sess(31))
        deltas_before = standby.stats["deltas"]
        standby.tick(20.0)
        assert standby.connected
        assert standby.stats["full_syncs"] == 2  # no third resync
        assert standby.stats["deltas"] == deltas_before + 2
        assert standby.store.get("s30") is not None
        assert standby.store.get("s31") is not None
        assert standby.last_seq == active._seq


class TestHealthFailover:
    def test_threshold_and_recovery(self):
        ok = {"v": True}
        events = []
        hm = HealthMonitor(lambda: ok["v"], interval_s=1.0,
                           failure_threshold=3, recovery_threshold=2,
                           on_event=events.append)
        for t in range(3):
            assert hm.tick(float(t)) == HealthState.HEALTHY
        ok["v"] = False
        hm.tick(3.0)
        hm.tick(4.0)
        assert hm.state == HealthState.DEGRADED
        hm.tick(5.0)
        assert hm.state == HealthState.FAILED
        assert events[-1].state == HealthState.FAILED
        ok["v"] = True
        hm.tick(6.0)
        assert hm.state == HealthState.FAILED  # 1 ok < recovery threshold
        hm.tick(7.0)
        assert hm.state == HealthState.HEALTHY

    def test_failover_and_auto_failback(self):
        roles = []
        fc = FailoverController(failover_delay_s=5.0, failback_delay_s=10.0,
                                on_role_change=roles.append)
        ok = {"v": True}
        hm = HealthMonitor(lambda: ok["v"], interval_s=1.0,
                           failure_threshold=2, on_event=fc.handle_health_event)
        ok["v"] = False
        hm.tick(1.0)
        hm.tick(2.0)  # -> FAILED event
        assert fc.state == FailoverState.FAILOVER_PENDING
        fc.tick(4.0)
        assert fc.role == Role.STANDBY  # grace not elapsed
        fc.tick(7.5)
        assert fc.role == Role.ACTIVE
        assert fc.state == FailoverState.FAILED_OVER
        assert roles == [Role.ACTIVE]
        # peer recovers -> failback after stability window
        ok["v"] = True
        hm.tick(8.0)
        hm.tick(9.0)
        assert fc.state == FailoverState.FAILBACK_PENDING
        fc.tick(18.0)
        assert fc.role == Role.ACTIVE  # window not elapsed
        fc.tick(19.5)
        assert fc.role == Role.STANDBY
        assert roles == [Role.ACTIVE, Role.STANDBY]

    def test_flap_cancels_pending_failover(self):
        fc = FailoverController(failover_delay_s=5.0)
        ok = {"v": False}
        hm = HealthMonitor(lambda: ok["v"], interval_s=1.0,
                           failure_threshold=2, recovery_threshold=1,
                           on_event=fc.handle_health_event)
        hm.tick(1.0)
        hm.tick(2.0)
        assert fc.state == FailoverState.FAILOVER_PENDING
        ok["v"] = True
        hm.tick(3.0)
        assert fc.state == FailoverState.NORMAL
        fc.tick(100.0)
        assert fc.role == Role.STANDBY


class TestResilience:
    def test_partition_lifecycle_with_conflicts(self):
        healthy = {"v": True}
        central = {}  # ip -> (subscriber, at)
        renumbered = []
        states = []
        m = ResilienceManager(
            nexus_healthy=lambda: healthy["v"],
            check_interval_s=1.0, failure_threshold=2,
            central_lookup=central.get,
            renumber=lambda sub: renumbered.append(sub) or True,
            on_state_change=states.append,
        )
        assert m.tick(1.0) == PartitionState.NORMAL
        healthy["v"] = False
        m.tick(2.0)
        assert m.state == PartitionState.NORMAL  # 1 fail < threshold
        m.tick(3.0)
        assert m.state == PartitionState.PARTITIONED
        # local allocations during partition
        m.record_allocation("sub-local", 0x0A000005, at=100.0)
        m.record_allocation("sub-free", 0x0A000006, at=101.0)
        # central store meanwhile gave .5 to someone else EARLIER
        central[0x0A000005] = ("sub-remote", 50.0)
        healthy["v"] = True
        m.tick(4.0)
        assert m.state == PartitionState.NORMAL
        # remote allocation was earlier -> local loses, gets renumbered
        assert renumbered == ["sub-local"]
        assert m.events.conflicts_found == 1
        assert m.events.renumbered == 1
        assert states == [PartitionState.PARTITIONED, PartitionState.RECOVERING,
                          PartitionState.NORMAL]

    def test_conflict_winner_by_timestamp(self):
        cd = ConflictDetector()
        cd.record("local", 1, at=10.0)
        out = cd.detect(lambda ip: ("remote", 20.0) if ip == 1 else None)
        assert out[0].winner == "local" and out[0].loser == "remote"
        cd2 = ConflictDetector()
        cd2.record("local", 1, at=30.0)
        out2 = cd2.detect(lambda ip: ("remote", 20.0))
        assert out2[0].winner == "remote" and out2[0].loser == "local"

    def test_pool_monitor_short_lease(self):
        util = {"v": 0.5}
        levels = []
        pm = PoolMonitor(lambda: util["v"], on_level_change=levels.append)
        assert pm.tick() == PoolLevel.NORMAL
        util["v"] = 0.85
        assert pm.tick() == PoolLevel.WARNING
        assert not pm.short_lease_active
        util["v"] = 0.96
        assert pm.tick() == PoolLevel.CRITICAL
        assert pm.short_lease_active
        util["v"] = 1.0
        assert pm.tick() == PoolLevel.EXHAUSTED
        util["v"] = 0.3
        assert pm.tick() == PoolLevel.NORMAL
        assert levels == [PoolLevel.WARNING, PoolLevel.CRITICAL,
                          PoolLevel.EXHAUSTED, PoolLevel.NORMAL]

    def test_degraded_auth_and_replay(self):
        h = DegradedRADIUSHandler(cache_ttl_s=100.0)
        h.cache_profile(CachedProfile("alice", "gold", cached_at=0.0))
        assert h.degraded_auth("alice", 50.0) is not None
        assert h.degraded_auth("alice", 200.0) is None  # TTL expired
        assert h.degraded_auth("bob", 1.0) is None
        assert h.reauth_queue == ["alice"]
        h.buffer_accounting({"session": "s1"})
        h.buffer_accounting({"session": "s2"})
        sent_ok = []
        fail_first = {"v": True}

        def send(rec):
            if fail_first["v"]:
                fail_first["v"] = False
                return False
            sent_ok.append(rec)
            return True

        sent, reauthed = h.replay(send, reauth=lambda u: True)
        assert sent == 1 and reauthed == 1
        assert len(h.acct_buffer) == 1  # failed record stays
        assert h.reauth_queue == []

    def test_request_queue_bounded(self):
        q = RequestQueue(max_size=2)
        assert q.enqueue("put", {"a": 1})
        assert q.enqueue("put", {"a": 2})
        assert not q.enqueue("put", {"a": 3})
        assert q.dropped == 1
        done = q.drain(lambda kind, p: p["a"] == 1)
        assert done == 1 and len(q) == 1


def test_failback_cancelled_when_peer_dies_again():
    """FAILBACK_PENDING + peer fails again -> stay active (no dual-dead)."""
    from bng_tpu.control.ha import HealthEvent

    fc = FailoverController(failover_delay_s=1.0, failback_delay_s=10.0)
    fc.force_failover()
    assert fc.role == Role.ACTIVE
    fc.handle_health_event(HealthEvent(HealthState.HEALTHY, 100.0))
    assert fc.state == FailoverState.FAILBACK_PENDING
    fc.handle_health_event(HealthEvent(HealthState.FAILED, 105.0))
    assert fc.state == FailoverState.FAILED_OVER
    fc.tick(200.0)
    assert fc.role == Role.ACTIVE  # never demoted


def test_radius_only_outage_activates_degraded_auth():
    radius_ok = {"v": True}
    sent = []
    m = ResilienceManager(nexus_healthy=lambda: True,
                          radius_healthy=lambda: radius_ok["v"],
                          check_interval_s=1.0, failure_threshold=2)
    m.radius_handler.cache_profile(CachedProfile("alice", "gold", cached_at=0.0))
    m.tick(1.0)
    assert not m.degraded_auth_active
    radius_ok["v"] = False
    m.tick(2.0)
    m.tick(3.0)
    assert m.radius_down and m.degraded_auth_active
    assert m.state == PartitionState.NORMAL  # nexus fine: not partitioned
    m.radius_handler.buffer_accounting({"s": 1})
    radius_ok["v"] = True
    m.tick(4.0, acct_send=lambda r: sent.append(r) or True)
    assert not m.degraded_auth_active
    assert len(sent) == 1  # buffered accounting replayed on recovery
