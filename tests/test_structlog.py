"""Structured JSON logging (SURVEY §5: zap-parity observability)."""

import io
import json

from bng_tpu.utils import structlog


class TestStructlog:
    def test_json_lines_with_bound_and_call_fields(self):
        buf = io.StringIO()
        structlog.setup("debug", "json", stream=buf)
        log = structlog.get_logger("dhcp", component="dhcp-server")
        log.info("lease allocated", mac="02:aa", ip="10.0.0.9")
        log.bind(pool=1).warning("pool low", free=12)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert lines[0]["level"] == "info"
        assert lines[0]["logger"] == "bng.dhcp"
        assert lines[0]["msg"] == "lease allocated"
        assert lines[0]["component"] == "dhcp-server"
        assert lines[0]["mac"] == "02:aa" and lines[0]["ip"] == "10.0.0.9"
        assert lines[1]["pool"] == 1 and lines[1]["free"] == 12
        assert "ts" in lines[0]

    def test_level_filtering(self):
        buf = io.StringIO()
        structlog.setup("warning", "json", stream=buf)
        log = structlog.get_logger("x")
        log.info("hidden")
        log.error("shown", code=7)
        lines = buf.getvalue().splitlines()
        assert len(lines) == 1 and json.loads(lines[0])["code"] == 7

    def test_console_format(self):
        buf = io.StringIO()
        structlog.setup("info", "console", stream=buf)
        structlog.get_logger("y").info("hello", a=1)
        out = buf.getvalue()
        assert "hello" in out and "a=1" in out and not out.startswith("{")

    def test_app_logs_json(self):
        """BNGApp emits structured startup lines."""
        import contextlib
        import sys

        from bng_tpu.cli import BNGApp, BNGConfig

        buf = io.StringIO()
        # setup() targets stderr; rebind by calling setup with our stream
        # after construction is not enough — capture via a fresh setup first
        structlog.setup("info", "json", stream=buf)
        app = BNGApp(BNGConfig(metrics_enabled=False, dhcpv6_enabled=False,
                               slaac_enabled=False, log_level="info"))
        app.close()
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert any(l["msg"] == "engine built" for l in lines)
