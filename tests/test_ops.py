"""Zero-downtime operations: live fleet elasticity, rolling worker
restart, blue/green engine swap, the ops control wire and the
autoscaler — plus the checkpoint N->M worker-count transition matrix
(runtime/checkpoint.py's never-cold-start promise beyond the
fleet<->fleetless directions test_fleet already covers)."""

import json
import threading
import urllib.request

import pytest

from bng_tpu.chaos.faults import (FAIL, IO_ERROR, KILL, FaultPlan, FaultSpec,
                                  SimClock, armed)
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (_discover, _renew, _reply, _request,
                                     build_fleet, dora_with_retries, _mac)
from bng_tpu.control import dhcp_codec
from bng_tpu.control.opsctl import (AutoscaleConfig, FleetAutoscaler,
                                    OpsController, OpsServer, ctl_request)
from bng_tpu.runtime import checkpoint as ckpt_mod

pytestmark = pytest.mark.ops


def _ack_of(rep, want_ip):
    if rep is None:
        return False
    p = _reply(rep)
    return p.msg_type == dhcp_codec.ACK and p.yiaddr == want_ip


def _renew_all(fleet, clock, leased, xid=0x100):
    macs = sorted(leased)
    out = fleet.handle_batch(
        [(i, _renew(m, leased[m], xid + i)) for i, m in enumerate(macs)],
        now=clock.advance(30.0))
    return sum(1 for (_l, rep), m in zip(out, macs)
               if _ack_of(rep, leased[m]))


# ---------------------------------------------------------------------------
# live fleet elasticity
# ---------------------------------------------------------------------------

class TestFleetResize:
    def test_shrink_and_grow_keep_every_lease_and_offer(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(4, clock)
        macs = [_mac(100 + i) for i in range(20)]
        leased = dora_with_retries(fleet, macs, clock)
        assert len(leased) == 20
        # in-flight DORAs: DISCOVER sent, OFFER out, no REQUEST yet
        inflight = [_mac(900 + i) for i in range(5)]
        out = fleet.handle_batch(
            [(i, _discover(m, 50 + i)) for i, m in enumerate(inflight)],
            now=clock())
        offers = {m: _reply(rep).yiaddr for (_l, rep), m in zip(out, inflight)}

        rep = fleet.resize(2)
        assert rep["outcome"] == "ok"
        assert rep["leases_moved"] == 20 and rep["offers_moved"] == 5
        assert fleet.n == 2 and len(fleet._inline) == 2

        # the un-ACKed OFFERs complete on their NEW owners at the
        # offered address — zero dropped in-flight DORAs
        out = fleet.handle_batch(
            [(i, _request(m, offers[m], 60 + i))
             for i, m in enumerate(inflight)], now=clock())
        assert all(_ack_of(rep, offers[m])
                   for (_l, rep), m in zip(out, inflight))
        assert _renew_all(fleet, clock, leased) == 20

        # grow past the original count; everything still renews
        assert fleet.resize(5)["outcome"] == "ok"
        assert _renew_all(fleet, clock, leased, xid=0x200) == 20
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        assert audit.ok, audit.violations_by_kind()

    def test_resize_releases_unheld_slices(self):
        """Shrinking must hand un-leased slice addresses back to the
        parent pool, or repeated resizes leak the pool dry."""
        clock = SimClock()
        fleet, pools, _ = build_fleet(4, clock, slice_size=32)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(8)], clock)
        pool = pools.pools[1]
        used_before = pool.used
        rep = fleet.resize(2)
        assert rep["slices_freed"] > 0
        # after resize: parent usage = leases + the new fleet's carves;
        # repeated resizes must not grow it monotonically
        for n in (3, 2, 4, 2):
            assert fleet.resize(n)["outcome"] == "ok"
        assert pool.used <= used_before
        assert _renew_all(fleet, clock, leased) == 8

    def test_resize_noop_and_validation(self):
        clock = SimClock()
        fleet, _pools, _ = build_fleet(2, clock)
        assert fleet.resize(2)["outcome"] == "noop"
        with pytest.raises(ValueError):
            fleet.resize(0)

    def test_admission_protection_survives_resize(self):
        """REQUEST-after-OFFER must never shed ACROSS a transition: the
        admission controller's known-client set is parent-side state."""
        clock = SimClock()
        fleet, _pools, _ = build_fleet(3, clock)
        m = _mac(77)
        out = fleet.handle_batch([(0, _discover(m, 1))], now=clock())
        ip = _reply(out[0][1]).yiaddr
        mac_u64 = int.from_bytes(m, "big")
        assert fleet.admission.is_known(mac_u64, clock())
        fleet.resize(5)
        assert fleet.admission.is_known(mac_u64, clock())
        out = fleet.handle_batch([(0, _request(m, ip, 2))], now=clock())
        assert _ack_of(out[0][1], ip)

    def test_chaos_fail_aborts_with_old_fleet_serving(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(3, clock)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(9)], clock)
        with armed(FaultPlan(1, [FaultSpec("fleet.resize", FAIL)]),
                   log=False):
            rep = fleet.resize(2)
        assert rep["outcome"] == "aborted"
        assert fleet.n == 3  # untouched, still serving
        assert _renew_all(fleet, clock, leased) == 9
        assert audit_invariants(pools=pools, fleet=fleet,
                                fastpath=fastpath).ok

    @pytest.mark.parametrize("fails,expect_n", [(1, 3), (2, 1)])
    def test_salvage_past_commit_point(self, fails, expect_n):
        """Past phase 2 the old fleet is gone and the exported books are
        the ONLY copy of every lease — a spawn/grant failure there must
        salvage them into SOME worker set (retry at target, then shrink
        to 1), never abandon them."""
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(2, clock)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(10)],
                                   clock)
        calls = {"n": 0}
        orig = fleet._initial_grant

        def flaky_grant():
            calls["n"] += 1
            if calls["n"] <= fails:
                raise RuntimeError("injected: grant infra down")
            return orig()

        fleet._initial_grant = flaky_grant
        rep = fleet.resize(3)
        assert rep["outcome"] == "salvaged", rep
        assert rep["to"] == expect_n and fleet.n == expect_n
        assert "RuntimeError" in rep["error"]
        assert rep["leases_moved"] == 10
        # every lease survived into the salvaged fleet
        assert _renew_all(fleet, clock, leased) == 10
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        assert audit.ok, audit.violations_by_kind()

    def test_chaos_kill_mid_resize_heals_inline_shard(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(4, clock)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(16)],
                                   clock)
        with armed(FaultPlan(1, [FaultSpec("fleet.resize", KILL,
                                           at_hit=2)]), log=False) as inj:
            rep = fleet.resize(2)
        assert inj.injected and rep["outcome"] == "ok"
        # the killed worker's book was still knowable inline: no loss
        assert rep["leases_moved"] == 16 and not rep["lost_workers"]
        assert not fleet._dead  # fresh fleet, all alive
        assert _renew_all(fleet, clock, leased) == 16
        assert audit_invariants(pools=pools, fleet=fleet,
                                fastpath=fastpath).ok


class TestRollingRestart:
    def test_books_offers_and_slices_move_verbatim(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(3, clock)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(12)],
                                   clock)
        m = _mac(800)
        out = fleet.handle_batch([(0, _discover(m, 9))], now=clock())
        offered = _reply(out[0][1]).yiaddr
        rep = fleet.rolling_restart()
        assert rep["outcome"] == "ok"
        assert rep["replaced"] == [0, 1, 2] and not rep["lost"]
        out = fleet.handle_batch([(0, _request(m, offered, 10))],
                                 now=clock())
        assert _ack_of(out[0][1], offered)
        assert _renew_all(fleet, clock, leased) == 12
        assert audit_invariants(pools=pools, fleet=fleet,
                                fastpath=fastpath).ok

    def test_restart_heals_a_chaos_killed_worker(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(3, clock)
        leased = dora_with_retries(fleet, [_mac(i) for i in range(12)],
                                   clock)
        fleet._kill_worker(1)
        assert 1 in fleet._dead
        rep = fleet.rolling_restart()
        assert rep["outcome"] == "ok" and rep["healed"] == [1]
        assert not fleet._dead
        assert _renew_all(fleet, clock, leased) == 12
        assert audit_invariants(pools=pools, fleet=fleet,
                                fastpath=fastpath).ok


# ---------------------------------------------------------------------------
# checkpoint restore across --slowpath-workers N -> M (never-cold-start)
# ---------------------------------------------------------------------------

class TestCheckpointWorkerCountMatrix:
    def _leased_fleet(self, n, n_macs=18):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(n, clock)
        leased = dora_with_retries(
            fleet, [_mac(i) for i in range(n_macs)], clock)
        assert len(leased) == n_macs
        return clock, fleet, pools, fastpath, leased

    def _roundtrip(self, fleet):
        ck = ckpt_mod.build_checkpoint(1, 1.0, fleet=fleet)
        return ckpt_mod.decode_checkpoint(ckpt_mod.encode_checkpoint(ck))

    @pytest.mark.parametrize("n_from,n_to", [(4, 2), (2, 5), (3, 3)])
    def test_fleet_to_fleet_n_to_m(self, n_from, n_to):
        _clock, fleet, _pools, _fp, leased = self._leased_fleet(n_from)
        dec = self._roundtrip(fleet)
        clock2 = SimClock()
        fleet2, pools2, fastpath2 = build_fleet(n_to, clock2)
        rows = ckpt_mod.restore_checkpoint(dec, fleet=fleet2)
        assert rows["fleet.leases"] == len(leased)
        assert _renew_all(fleet2, clock2, leased) == len(leased)
        audit = audit_invariants(pools=pools2, fleet=fleet2,
                                 fastpath=fastpath2)
        assert audit.ok, audit.violations_by_kind()

    def test_n_to_1_to_n_chain(self):
        """The full round trip the promise covers: fleet -> fleetless
        single worker -> fleet again, leases surviving every hop."""
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.chaos.scenarios import (SERVER_IP, SERVER_MAC,
                                             _make_pools)

        _clock, fleet, _pools, _fp, leased = self._leased_fleet(4)
        dec = self._roundtrip(fleet)
        # hop 1: N -> 1 (fleetless): worker books merge into the parent
        pools_b = _make_pools()
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools_b)
        rows = ckpt_mod.restore_checkpoint(dec, dhcp=server)
        assert rows["dhcp.leases"] == len(leased)
        # hop 2: 1 -> N: the parent book re-shards into a NEW fleet
        dec2 = ckpt_mod.decode_checkpoint(ckpt_mod.encode_checkpoint(
            ckpt_mod.build_checkpoint(2, 2.0, dhcp=server)))
        clock3 = SimClock()
        fleet3, pools3, fastpath3 = build_fleet(3, clock3)
        rows = ckpt_mod.restore_checkpoint(dec2, fleet=fleet3)
        assert rows["fleet.leases"] == len(leased)
        assert _renew_all(fleet3, clock3, leased) == len(leased)
        audit = audit_invariants(pools=pools3, fleet=fleet3,
                                 fastpath=fastpath3)
        assert audit.ok, audit.violations_by_kind()

    def test_live_resize_then_checkpoint_roundtrip(self):
        """A fleet that has been live-resized checkpoints/restores like
        any other — the two transition paths share one hydration core."""
        clock, fleet, _pools, _fp, leased = self._leased_fleet(4)
        assert fleet.resize(2)["outcome"] == "ok"
        dec = self._roundtrip(fleet)
        clock2 = SimClock()
        fleet2, pools2, fastpath2 = build_fleet(4, clock2)
        assert ckpt_mod.restore_checkpoint(
            dec, fleet=fleet2)["fleet.leases"] == len(leased)
        assert _renew_all(fleet2, clock2, leased) == len(leased)
        assert audit_invariants(pools=pools2, fleet=fleet2,
                                fastpath=fastpath2).ok


# ---------------------------------------------------------------------------
# blue/green engine swap (compiles the fused pipeline once per session)
# ---------------------------------------------------------------------------

def _engine_stack():
    from bng_tpu.chaos.scenarios import _build_server_stack
    from bng_tpu.runtime.engine import Engine

    clock = SimClock()
    server, pools, fastpath, nat = _build_server_stack(clock)
    eng = Engine(fastpath, nat, batch_size=32,
                 slow_path=server.handle_frame, clock=clock)
    leased = {}
    for i in range(5):
        m = _mac(300 + i)
        out = eng.process([_discover(m, 100 + i)])
        ip = _reply((out["slow"] or out["tx"])[0][1]).yiaddr
        eng.process([_request(m, ip, 200 + i)])
        leased[m] = ip
    return clock, server, pools, fastpath, nat, eng, leased


class TestBlueGreenSwap:
    def test_swap_flips_and_serves_on_device(self):
        from bng_tpu.runtime.ops import blue_green_swap

        clock, server, pools, _fp, nat, eng, leased = _engine_stack()
        components = {"engine": eng, "pools": pools, "dhcp": server}
        rep = blue_green_swap(components)
        assert rep["outcome"] == "ok" and rep["audit_ok"]
        standby = components["engine"]
        assert standby is not eng
        assert standby.stats is eng.stats  # counter continuity
        # renewals answered ON DEVICE from the hydrated standby chain
        m = next(iter(sorted(leased)))
        out = standby.process([_renew(m, leased[m], 0xA01)],
                              now=clock.advance(30.0))
        assert out["tx"] and _ack_of(out["tx"][0][1], leased[m])
        assert audit_invariants(engine=standby, pools=pools, dhcp=server,
                                nat=nat).ok

    def test_crash_mid_swap_rolls_back(self):
        from bng_tpu.runtime.ops import blue_green_swap

        clock, server, pools, _fp, nat, eng, leased = _engine_stack()
        components = {"engine": eng, "pools": pools, "dhcp": server}
        with armed(FaultPlan(1, [FaultSpec("ops.swap", FAIL)]), log=False):
            rep = blue_green_swap(components)
        assert rep["outcome"] == "rolled_back"
        assert components["engine"] is eng  # active untouched
        m = next(iter(sorted(leased)))
        out = eng.process([_renew(m, leased[m], 0xA02)],
                          now=clock.advance(30.0))
        assert _ack_of((out["tx"] or out["slow"])[0][1], leased[m])
        assert audit_invariants(engine=eng, pools=pools, dhcp=server,
                                nat=nat).ok

    def test_unexpected_error_after_delta_still_heals_active(self, monkeypatch):
        """The rollback heal must run for ANY exception once the replay
        consumed dirty marks into the discarded standby — an XLA runtime
        error is a plain RuntimeError, not one of the expected types, and
        escaping without eng.resync_tables() would leave the active
        device chain silently missing those rows."""
        from bng_tpu import chaos
        from bng_tpu.runtime.ops import blue_green_swap

        clock, server, pools, _fp, nat, eng, leased = _engine_stack()
        components = {"engine": eng, "pools": pools, "dhcp": server}

        def exploding_audit(*a, **kw):
            raise RuntimeError("injected: device backend fell over")

        monkeypatch.setattr(chaos.invariants, "audit_invariants",
                            exploding_audit)
        rep = blue_green_swap(components)
        monkeypatch.undo()
        assert rep["outcome"] == "rolled_back", rep
        assert "RuntimeError" in rep["error"]
        assert components["engine"] is eng  # active untouched
        # the heal ran: host == device on the ACTIVE chain, still serving
        m = next(iter(sorted(leased)))
        out = eng.process([_renew(m, leased[m], 0xA05)],
                          now=clock.advance(30.0))
        assert _ack_of((out["tx"] or out["slow"])[0][1], leased[m])
        assert audit_invariants(engine=eng, pools=pools, dhcp=server,
                                nat=nat).ok

    def test_snapshot_io_error_fails_before_standby(self):
        from bng_tpu.runtime.ops import blue_green_swap

        _clock, server, pools, _fp, _nat, eng, _leased = _engine_stack()
        components = {"engine": eng, "pools": pools, "dhcp": server}
        with armed(FaultPlan(1, [FaultSpec("ops.snapshot", IO_ERROR)]),
                   log=False):
            rep = blue_green_swap(components)
        assert rep["outcome"] == "failed"
        assert "OSError" in rep["error"]
        assert components["engine"] is eng

    def test_delta_replay_ships_post_snapshot_rows(self):
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.ops import clone_mirrors, replay_delta_since

        clock, server, pools, fastpath, nat, eng, _leased = _engine_stack()
        eng.quiesce()
        eng.fold_device_authoritative()
        ck = ckpt_mod.roundtrip_checkpoint(ckpt_mod.build_checkpoint(
            0, clock(), fastpath=fastpath, nat=nat, qos=eng.qos,
            antispoof=eng.antispoof))
        # mutate AFTER the snapshot: one more subscriber leases
        m = _mac(999)
        out = eng.process([_discover(m, 0xB00)])
        ip = _reply((out["slow"] or out["tx"])[0][1]).yiaddr
        eng.process([_request(m, ip, 0xB01)])
        eng.quiesce()
        tmp = clone_mirrors(eng)
        ckpt_mod.restore_checkpoint(ck, **tmp)
        hydrator = Engine(tmp["fastpath"], tmp["nat"], qos=tmp["qos"],
                          antispoof=tmp["antispoof"], batch_size=eng.B,
                          clock=clock)
        standby = Engine(fastpath, nat, qos=eng.qos,
                         antispoof=eng.antispoof, batch_size=eng.B,
                         slow_path=server.handle_frame, clock=clock)
        standby.adopt_device_tables(hydrator.tables)
        d = replay_delta_since(standby, ck.arrays)
        assert d["rows"] > 0 and not d["resync"]
        assert standby.pending_dirty() == 0
        # host == device bit-exact after the replay (the mirror audit)
        audit = audit_invariants(engine=standby, pools=pools, dhcp=server,
                                 nat=nat)
        assert audit.ok, audit.violations_by_kind()

    def test_swap_with_scheduler_repoints_lanes(self):
        from bng_tpu.runtime.ops import blue_green_swap
        from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler

        clock, server, pools, _fp, nat, eng, leased = _engine_stack()
        sched = TieredScheduler(eng, SchedulerConfig(bulk_batch=32),
                                clock=clock)
        components = {"engine": eng, "scheduler": sched, "pools": pools,
                      "dhcp": server}
        rep = blue_green_swap(components)
        assert rep["outcome"] == "ok"
        assert sched.engine is components["engine"]
        m = next(iter(sorted(leased)))
        res = sched.process([_renew(m, leased[m], 0xA03)],
                            now=clock.advance(30.0))
        got = res["tx"] or res["slow"]
        assert got and _ack_of(got[0][1], leased[m])


# ---------------------------------------------------------------------------
# the ops control wire (`bng ctl`) + app-level transitions
# ---------------------------------------------------------------------------

class TestOpsControl:
    def _app(self, **kw):
        from bng_tpu.cli import BNGApp, BNGConfig

        cfg = BNGConfig(slowpath_workers=2, slowpath_worker_mode="inline",
                        dhcpv6_enabled=False, slaac_enabled=False,
                        metrics_enabled=True, ctl_listen="", **kw)
        return BNGApp(cfg)

    def test_app_fleet_resize_and_status(self):
        app = self._app()
        try:
            assert app.components["fleet"].n == 2
            rep = app.fleet_resize(4)
            assert rep["outcome"] == "ok"
            assert app.components["fleet"].n == 4
            st = app.ops_status()
            assert st["fleet"]["workers"] == 4
            assert st["fleet"]["resizes"] == 1
            # transition metrics recorded
            m = app.components["metrics"]
            assert m.ops_transitions.value(op="fleet_resize",
                                           outcome="ok") == 1
        finally:
            app.close()

    def test_app_rejects_resize_without_fleet(self):
        from bng_tpu.cli import BNGApp, BNGConfig

        app = BNGApp(BNGConfig(slowpath_workers=4, pppoe_enabled=True,
                               dhcpv6_enabled=False, slaac_enabled=False,
                               metrics_enabled=True))
        try:
            assert app.fleet_blockers == ["pppoe"]
            assert "slowpath_fleet_blocked" in app.stats()
            rep = app.fleet_resize(8)
            assert rep["outcome"] == "rejected" and "pppoe" in rep["error"]
            # the degradation is a labeled gauge, not just a log line
            m = app.components["metrics"]
            assert m.slowpath_fleet_blocked.value(blocker="pppoe") == 1
        finally:
            app.close()

    def test_ha_active_composes_with_fleet(self):
        """`ha` left the blocker list: an active-role app with a
        configured fleet builds BOTH, and worker lease events reach the
        ActiveSyncer store through the fleet's lease_hook relay."""
        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.control import dhcp_codec, packets

        app = BNGApp(BNGConfig(slowpath_workers=2, ha_role="active",
                               dhcpv6_enabled=False, slaac_enabled=False,
                               metrics_enabled=True))
        try:
            assert app.fleet_blockers == []
            fleet = app.components["fleet"]
            assert fleet.n == 2
            ha_store = app.components["ha_store"]
            assert len(ha_store) == 0

            mac = bytes.fromhex("02aa00000042")
            disc = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER,
                                            xid=1)
            frame = packets.udp_packet(
                mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                disc.encode().ljust(300, b"\x00"))
            (_l, rep), = fleet.handle_batch([(0, frame)], now=1.0)
            off = dhcp_codec.decode(packets.decode(rep).payload)
            assert off.msg_type == dhcp_codec.OFFER
            req = dhcp_codec.build_request(
                mac, dhcp_codec.REQUEST, xid=2, requested_ip=off.yiaddr,
                server_id=off.server_id)
            frame = packets.udp_packet(
                mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                req.encode().ljust(300, b"\x00"))
            (_l, rep), = fleet.handle_batch([(0, frame)], now=1.0)
            ack = dhcp_codec.decode(packets.decode(rep).payload)
            assert ack.msg_type == dhcp_codec.ACK

            # the worker's lease event crossed the single-writer drain
            # into the active's replicated session store
            assert len(ha_store) == 1
            (sess,) = ha_store.all()
            assert sess.mac == mac.hex() and sess.ip == ack.yiaddr
        finally:
            app.close()

    def test_ctl_http_roundtrip(self):
        """The full wire: OpsServer -> OpsController queue -> run-loop
        pump -> fleet.resize -> report back over HTTP."""
        app = self._app()
        srv = None
        stop = threading.Event()
        try:
            ops = app.components["ops"]
            srv = OpsServer(ops, "127.0.0.1", 0).start()
            addr = f"{srv.addr[0]}:{srv.addr[1]}"

            def pump():
                while not stop.is_set():
                    ops.run_pending()
                    stop.wait(0.01)

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            code, doc = ctl_request(addr, "fleet/resize", {"n": 3},
                                    timeout_s=30)
            assert code == 200 and doc["outcome"] == "ok"
            assert app.components["fleet"].n == 3
            code, doc = ctl_request(addr, "status")
            assert code == 200 and doc["fleet"]["workers"] == 3
            code, doc = ctl_request(addr, "fleet/rolling-restart", {})
            assert code == 200 and doc["outcome"] == "ok"
            # unknown op rejects without touching the queue
            code, doc = ctl_request(addr, "bogus", {})
            assert code == 409 and doc["outcome"] == "rejected"
        finally:
            stop.set()
            if srv is not None:
                srv.close()
            app.close()

    def test_timeout_race_with_executing_loop_returns_real_report(self):
        """When the run loop claims an op right at the client's
        deadline, the client must NOT be told 'timeout' (it would retry
        and double the transition) — the atomic claim makes exactly one
        side win, and the losing client waits for the real report."""
        import time as _time

        app = self._app()
        try:
            ops = app.components["ops"]
            orig = app.fleet_resize

            def slow_resize(n):
                _time.sleep(0.4)  # loop holds the op past the deadline
                return orig(n)

            app.fleet_resize = slow_resize
            stop = threading.Event()

            def pump():
                while not stop.is_set():
                    ops.run_pending()
                    _time.sleep(0.001)

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            # the pump dequeues within ~1ms and executes for 0.4s; the
            # 0.1s client deadline expires mid-execution — the loop owns
            # the claim, so submit waits it out and returns the report
            rep = ops.submit("fleet/resize", {"n": 3}, timeout_s=0.1)
            stop.set()
            t.join(timeout=5)
            assert rep["outcome"] == "ok", rep
            assert app.components["fleet"].n == 3
            # exactly ONE transition executed
            assert app.components["fleet"].resizes == 1
        finally:
            app.close()

    def test_run_pending_skips_client_claimed_entry(self):
        """The loop side of the same claim: an entry the client already
        claimed (timed out) must be skipped, never executed."""
        app = self._app()
        try:
            ops = app.components["ops"]
            done = threading.Event()
            ops._q.put_nowait(("fleet_resize", {"n": 3}, done,
                               {"owner": "client"}))
            assert ops.run_pending() == 0
            assert done.is_set()  # the skip still releases the waiter
            assert app.components["fleet"].n == 2
        finally:
            app.close()

    def test_controller_timeout_when_nothing_pumps(self):
        app = self._app()
        try:
            ops = app.components["ops"]
            fleet = app.components["fleet"]
            rep = ops.submit("fleet/resize", {"n": 3}, timeout_s=0.05)
            assert rep["outcome"] == "timeout"
            # the timed-out op was CANCELLED, not abandoned: when the
            # loop finally drains, it must not fire (an operator retry
            # after a timeout would otherwise double the transition)
            assert ops.run_pending() == 0
            assert fleet.n == 2
            assert ops.stats_snapshot()["rejected"] == 1
        finally:
            app.close()


class TestAutoscaler:
    def _fleet(self):
        clock = SimClock()
        fleet, _pools, _ = build_fleet(2, clock)
        return clock, fleet

    def test_scales_up_on_shed(self):
        clock, fleet = self._fleet()
        auto = FleetAutoscaler(fleet, AutoscaleConfig(max_workers=4,
                                                      cooldown_s=0.0),
                               clock=clock)
        assert auto.target(clock()) is None  # first look only baselines
        fleet.admission.stats.shed["inbox_full"] = 5
        clock.advance(1.0)
        assert auto.target(clock()) == 3

    def test_scales_down_only_after_hold(self):
        clock, fleet = self._fleet()
        auto = FleetAutoscaler(
            fleet, AutoscaleConfig(min_workers=1, max_workers=4, hold=3,
                                   cooldown_s=0.0), clock=clock)
        auto.target(clock())
        downs = []
        for _ in range(6):
            clock.advance(1.0)
            got = auto.target(clock())
            if got is not None:
                downs.append(got)
        # calm fleet: exactly one step down per `hold` calm looks
        assert downs and downs[0] == 1

    def test_cooldown_blocks_thrash(self):
        clock, fleet = self._fleet()
        auto = FleetAutoscaler(fleet, AutoscaleConfig(max_workers=8,
                                                      cooldown_s=60.0),
                               clock=clock)
        auto.target(clock())
        fleet.admission.stats.shed["inbox_full"] = 5
        clock.advance(1.0)
        assert auto.target(clock()) == 3
        fleet.admission.stats.shed["inbox_full"] = 50
        clock.advance(1.0)
        assert auto.target(clock()) is None  # inside the cooldown

    def test_transition_reset_never_credits_calm(self):
        """resize/rolling_restart zero the per-worker stats payloads, so
        busy_seconds_total() goes BACKWARD across a transition — that
        look must re-baseline and decide nothing, not bank a bogus
        'calm' hysteresis credit while the fleet may be saturated."""
        clock, fleet = self._fleet()
        auto = FleetAutoscaler(
            fleet, AutoscaleConfig(min_workers=1, max_workers=4, hold=2,
                                   cooldown_s=0.0), clock=clock)
        auto.target(clock())  # baseline
        # busy fleet: mid-band fraction (no decision, calm resets)
        fleet._last_stats = [{"busy_s": 1.0}, {"busy_s": 1.0}]
        clock.advance(2.0)
        assert auto.target(clock()) is None and auto._calm == 0
        # a transition resets the stats: counter goes backward
        fleet._last_stats = [{}, {}]
        clock.advance(1.0)
        assert auto.target(clock()) is None
        assert auto._calm == 0  # the reset look banked NO calm credit
        # from the fresh baseline, exactly `hold` genuinely-calm looks
        # are still required before a scale-down fires
        clock.advance(1.0)
        assert auto.target(clock()) is None and auto._calm == 1
        clock.advance(1.0)
        assert auto.target(clock()) == 1

    def test_autoscaler_resize_failure_keeps_tick_alive(self):
        """An autoscaler-triggered resize that raises must be contained
        by the tick loop — crashing the dataplane process on a failed
        grow is the outage the zero-downtime layer exists to prevent."""
        from bng_tpu.cli import BNGApp, BNGConfig

        app = BNGApp(BNGConfig(
            slowpath_workers=2, slowpath_worker_mode="inline",
            slowpath_autoscale=True, slowpath_max_workers=4,
            dhcpv6_enabled=False, slaac_enabled=False))
        try:
            fleet = app.components["fleet"]
            app.components["autoscaler"].cfg.cooldown_s = 0.0

            def exploding_resize(n):
                raise RuntimeError("injected: cannot spawn workers")

            fleet.resize = exploding_resize
            app.tick(1000.0)  # baseline look
            fleet.admission.stats.shed["inbox_full"] = 9
            app.tick(1001.0)  # recommends a grow; resize raises inside
            assert fleet.n == 2  # unchanged, and the loop survived
            app.tick(1002.0)  # loop still ticking
        finally:
            app.close()

    def test_app_tick_drives_autoscaler(self):
        from bng_tpu.cli import BNGApp, BNGConfig

        app = BNGApp(BNGConfig(
            slowpath_workers=2, slowpath_worker_mode="inline",
            slowpath_autoscale=True, slowpath_max_workers=4,
            dhcpv6_enabled=False, slaac_enabled=False))
        try:
            auto = app.components["autoscaler"]
            auto.cfg.cooldown_s = 0.0
            app.tick(1000.0)  # baseline look
            app.components["fleet"].admission.stats.shed["inbox_full"] = 9
            app.tick(1001.0)
            assert app.components["fleet"].n == 3
        finally:
            app.close()


# ---------------------------------------------------------------------------
# the acceptance bar: live transitions on a RUNNING composed app —
# traffic before, transitions at the boundary, traffic after, audit-clean
# epilogue, one process throughout
# ---------------------------------------------------------------------------

class TestLiveAppTransitions:
    def test_resize_and_swap_on_a_driving_app(self):
        from bng_tpu.chaos.invariants import audit_app
        from bng_tpu.cli import BNGApp, BNGConfig

        app = BNGApp(BNGConfig(
            synthetic_subs=32, batch_size=32,
            slowpath_workers=2, slowpath_worker_mode="inline",
            dhcpv6_enabled=False, slaac_enabled=False, ctl_listen=""))
        try:
            fleet = app.components["fleet"]
            engine_before = app.components["engine"]

            def drive(beats):
                moved = 0
                for _ in range(beats):
                    moved += app.drive_once()
                return moved

            assert drive(12) > 0
            served_before = app.components["dhcp"].stats.offer \
                + sum(w.server.stats.offer for w in fleet._inline)
            assert served_before > 0

            # live resize between beats — the batch boundary the run
            # loop's ops pump uses
            rep = app.fleet_resize(3)
            assert rep["outcome"] == "ok" and fleet.n == 3
            assert drive(12) > 0

            # blue/green engine swap on the same still-running process
            rep = app.engine_swap()
            assert rep["outcome"] == "ok", rep
            assert app.components["engine"] is not engine_before
            assert drive(12) > 0

            rep = app.fleet_rolling_restart()
            assert rep["outcome"] == "ok"
            assert drive(12) > 0

            # audit-clean epilogue over the live, post-transition app
            audit = audit_app(app)
            assert audit.ok, audit.violations_by_kind()
            # traffic kept flowing across every transition (no restart:
            # the same engine stats object accumulated throughout)
            assert app.components["engine"].stats.batches > 0
        finally:
            app.close()


# ---------------------------------------------------------------------------
# the requeue satellite: public pending-queue API
# ---------------------------------------------------------------------------

class TestRequeue:
    def test_demux_requeue_order(self):
        from bng_tpu.control.slowpath import SlowPathDemux

        d = SlowPathDemux()
        d.requeue([b"b", b"c"])
        d.requeue([b"a"], front=True)
        assert d.drain_pending() == [b"a", b"b", b"c"]
        assert d.drain_pending() == []

    def test_fleet_requeue_order(self):
        clock = SimClock()
        fleet, _pools, _ = build_fleet(2, clock)
        fleet.requeue([b"y"])
        fleet.requeue([b"x"], front=True)
        assert fleet.drain_pending() == [b"x", b"y"]
