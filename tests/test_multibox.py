"""Multi-box deployment (ISSUE 20): the fabric state-handoff protocol,
remote serving members, and host-loss promotion.

Covers the handoff corruption matrix (truncated / bit-flipped /
replayed / out-of-order chunks -> reject-to-re-request, never partial
acceptance), resume-from-ACK-cursor byte identity, the lossy
SimTransport transfer loop, the full sim-mode join -> hydrate -> serve
-> host-loss flow (missteers == 0, group promotion, sticky renewals,
clean audit), the `--join` backoff/give-up discipline, the fleet's
worker-local Nexus allocation lane, the member/handoff metrics
families, and (slow tier) the two-process loopback e2e: a real
`bng cluster run --join` subprocess pair SIGKILLed as a host group.

`make verify-multibox` runs this file (`multibox` marker, <60s); the
tier-1 Makefile line deselects the marker so the suite runs once. The
subprocess e2e is additionally @slow."""

import base64
import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import zlib

import pytest

from bng_tpu.chaos.faults import SimClock
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import _mac, _renew, _reply, dora_with_retries
from bng_tpu.cluster import (ClusterCoordinator, MemberRuntime,
                             instance_for_mac)
from bng_tpu.cluster.fabric import SimTransport
from bng_tpu.cluster.handoff import (HandoffError, HandoffManager,
                                     StateReceiver, StateSender,
                                     build_handoff_checkpoint,
                                     parse_handoff_checkpoint)
from bng_tpu.cluster.handoff.protocol import (KIND_ACK, KIND_CHUNK,
                                              KIND_MANIFEST)
from bng_tpu.control import dhcp_codec
from bng_tpu.utils.net import ip_to_u32, u32_to_ip

pytestmark = pytest.mark.multibox

SPACE = ip_to_u32("10.112.0.0")


# ---------------------------------------------------------------------------
# handoff wire helpers (direct receiver/sender drive, no transport loop)
# ---------------------------------------------------------------------------

class _Wire:
    """Capture transport: records every (dst, kind, body) send."""

    def __init__(self):
        self.sent = []

    def send(self, dst, kind, body):
        self.sent.append((dst, kind, body))

    def take(self):
        out, self.sent = self.sent, []
        return out

    def acks(self):
        return [b for _d, k, b in self.sent if k == KIND_ACK]


def _payload(n=6000, seed=1):
    return bytes((i * 131 + seed * 17) % 256 for i in range(n))


def _manifest_body(data, xid="x-1", chunk_size=512, digest=None):
    n = max(1, (len(data) + chunk_size - 1) // chunk_size)
    return {"xid": xid, "kind": "carve", "total_len": len(data),
            "n_chunks": n, "chunk_size": chunk_size,
            "digest": digest or hashlib.sha256(data).hexdigest(),
            "meta": {}}


def _chunk_body(data, seq, xid="x-1", chunk_size=512, raw=None, crc=None):
    """One chunk frame; `raw` overrides the payload while `crc` stays
    the TRUE slice's CRC — the tamper hook for corruption tests."""
    true = data[seq * chunk_size: (seq + 1) * chunk_size]
    return {"xid": xid, "seq": seq,
            "crc": (zlib.crc32(true) & 0xFFFFFFFF) if crc is None else crc,
            "data": base64.b64encode(true if raw is None
                                     else raw).decode("ascii")}


def _recv(wire=None, verify=None):
    got = {}
    r = StateReceiver(wire if wire is not None else _Wire(),
                      verify=verify,
                      on_complete=lambda s, man, d: got.update(
                          {"src": s, "man": man, "data": d}))
    return r, got


class TestHandoffCorruption:
    """Every corruption is reject-to-re-request: the receiver drops the
    bad frame, counts it, and re-acks its cursor — it never banks a
    byte it cannot prove."""

    def test_truncated_chunk_dropped_then_rerequested(self):
        data = _payload()
        wire = _Wire()
        r, got = _recv(wire)
        r.set_manifest("tx", _manifest_body(data))
        r.accept_chunk("tx", _chunk_body(data, 0))
        # chunk 1 truncated in flight: CRC can't match
        r.accept_chunk("tx", _chunk_body(data, 1,
                                         raw=data[512:1024 - 9]))
        assert r.stats["rx_corrupt"] == 1
        t = r.transfers[("tx", "x-1")]
        assert 1 not in t.chunks and t.cursor == 1
        assert wire.acks()[-1]["cursor"] == 1  # re-ack = re-request
        for seq in range(1, t.n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data
        assert r.stats["completed"] == 1 and r.stats["rejects"] == 0

    def test_bitflipped_chunk_dropped(self):
        data = _payload()
        r, got = _recv()
        r.set_manifest("tx", _manifest_body(data))
        bad = bytearray(data[0:512])
        bad[100] ^= 0x40
        r.accept_chunk("tx", _chunk_body(data, 0, raw=bytes(bad)))
        assert r.stats["rx_corrupt"] == 1
        assert r.transfers[("tx", "x-1")].chunks == {}
        for seq in range(r.transfers[("tx", "x-1")].n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data

    def test_bad_base64_counts_corrupt(self):
        data = _payload()
        r, _ = _recv()
        r.set_manifest("tx", _manifest_body(data))
        body = _chunk_body(data, 0)
        body["data"] = "!!not base64!!"
        r.accept_chunk("tx", body)
        assert r.stats["rx_corrupt"] == 1

    def test_replayed_chunk_reacks_cursor(self):
        # a replayed (duplicate) chunk means the sender lost an ack:
        # the receiver must re-teach it the cursor, not bank it twice
        data = _payload()
        wire = _Wire()
        r, got = _recv(wire)
        r.set_manifest("tx", _manifest_body(data))
        r.accept_chunk("tx", _chunk_body(data, 0))
        before = len(wire.acks())
        r.accept_chunk("tx", _chunk_body(data, 0))        # replay
        assert r.stats["rx_dup"] == 1
        assert len(wire.acks()) == before + 1
        assert wire.acks()[-1]["cursor"] == 1
        for seq in range(1, r.transfers[("tx", "x-1")].n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data
        # replay AFTER completion is a dup too, not a new transfer
        r.accept_chunk("tx", _chunk_body(data, 0))
        assert r.stats["rx_dup"] == 2

    def test_out_of_order_chunk_acks_the_gap_immediately(self):
        data = _payload()
        wire = _Wire()
        r, got = _recv(wire)
        r.set_manifest("tx", _manifest_body(data))
        r.accept_chunk("tx", _chunk_body(data, 3))
        ack = wire.acks()[-1]
        assert ack["cursor"] == 0 and ack["need"] == [0, 1, 2]
        for seq in (0, 1, 2):
            r.accept_chunk("tx", _chunk_body(data, seq))
        for seq in range(4, r.transfers[("tx", "x-1")].n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data and r.stats["completed"] == 1

    def test_orphan_chunk_without_manifest(self):
        r, _ = _recv()
        r.accept_chunk("tx", _chunk_body(_payload(), 0))
        assert r.stats["rx_orphan"] == 1

    def test_out_of_range_seq_is_orphan(self):
        data = _payload()
        r, _ = _recv()
        r.set_manifest("tx", _manifest_body(data))
        r.accept_chunk("tx", _chunk_body(data, 0, crc=0, raw=b"z") | {
            "seq": 999})
        assert r.stats["rx_orphan"] == 1

    def test_bad_geometry_manifest_dropped(self):
        r, _ = _recv()
        r.set_manifest("tx", {"xid": "x-1", "total_len": 10,
                              "n_chunks": 0, "chunk_size": 0,
                              "digest": "d", "meta": {}})
        assert r.stats["rx_orphan"] == 1 and r.transfers == {}

    def test_digest_mismatch_rejects_both_sides_to_zero(self):
        # the assembled payload fails the manifest digest: the receiver
        # wipes its chunks (cursor 0) and the reject ack resets the
        # sender, which restarts the stream with a fresh manifest
        data = _payload(2000)
        wire = _Wire()
        r, got = _recv(wire)
        r.set_manifest("tx", _manifest_body(data, digest="0" * 64))
        for seq in range(4):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert r.stats["rejects"] == 1 and "data" not in got
        t = r.transfers[("tx", "x-1")]
        assert t.chunks == {} and t.cursor == 0 and not t.complete
        rej = wire.acks()[-1]
        assert rej["reject"] and rej["cursor"] == 0
        swire = _Wire()
        s = StateSender(swire, "rx", "x-1", data, chunk_size=512,
                        clock=lambda: 0.0)
        s.on_ack({"xid": "x-1", "cursor": 2, "need": []})
        s.pump(0.0)
        assert s.acked == 2
        s.on_ack(rej)
        assert s.rejected == 1 and s.acked == 0 and s.sent_high == 0
        assert s.stats["manifests_tx"] == 2  # restarted from zero

    def test_checkpoint_gate_rejects_structurally_bad_payload(self):
        # digest matches (the bytes arrived faithfully) but the payload
        # is NOT a valid checkpoint: the hydration gate must refuse it
        data = b"not a checkpoint at all" * 50
        wire = _Wire()
        got = {}
        r = StateReceiver(wire, on_complete=lambda s, man, d: got.update(
            {"data": d}))  # default verify = checkpoint gate
        r.set_manifest("tx", _manifest_body(data))
        for seq in range(r.transfers[("tx", "x-1")].n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert r.stats["rejects"] == 1 and "data" not in got
        assert "checkpoint gate" in wire.acks()[-1]["reason"]

    def test_good_checkpoint_payload_passes_the_gate(self):
        data = build_handoff_checkpoint(3, {"cluster_plan": {"epoch": 3}})
        wire = _Wire()
        got = {}
        r = StateReceiver(wire, on_complete=lambda s, man, d: got.update(
            {"data": d}))
        r.set_manifest("tx", _manifest_body(data))
        for seq in range(r.transfers[("tx", "x-1")].n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data
        assert parse_handoff_checkpoint(got["data"]) == {
            "cluster_plan": {"epoch": 3}}

    def test_interrupted_transfer_resumes_from_ack_cursor(self):
        # sender dies mid-stream and a NEW sender (same payload, same
        # xid) re-manifests: the receiver keeps its banked chunks and
        # acks the cursor — the resume — and the assembly is
        # byte-identical to an uninterrupted transfer
        data = _payload(5120)
        wire = _Wire()
        r, got = _recv(wire)
        r.set_manifest("tx", _manifest_body(data))
        for seq in range(5):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert r.transfers[("tx", "x-1")].cursor == 5
        r.set_manifest("tx", _manifest_body(data))   # the restart
        assert r.stats["resumes"] == 1
        t = r.transfers[("tx", "x-1")]
        assert len(t.chunks) == 5 and t.cursor == 5  # nothing lost
        assert wire.acks()[-1]["cursor"] == 5        # sender skips 0-4
        for seq in range(5, t.n_chunks):
            r.accept_chunk("tx", _chunk_body(data, seq))
        assert got["data"] == data and r.stats["completed"] == 1

    def test_different_digest_restarts_clean(self):
        data, data2 = _payload(2048, seed=1), _payload(2048, seed=2)
        r, _ = _recv()
        r.set_manifest("tx", _manifest_body(data))
        r.accept_chunk("tx", _chunk_body(data, 0))
        r.set_manifest("tx", _manifest_body(data2))  # new content
        assert r.stats["resumes"] == 0
        assert r.transfers[("tx", "x-1")].chunks == {}

    def test_oversized_chunk_size_refused(self):
        with pytest.raises(HandoffError):
            StateSender(_Wire(), "rx", "x", b"abc", chunk_size=5121)
        with pytest.raises(HandoffError):
            StateSender(_Wire(), "rx", "x", b"abc", chunk_size=0)


class TestHandoffOverSimFabric:
    def test_lossy_transfer_completes_byte_identical(self):
        """30% drop each way: the window/need/retransmit machinery must
        converge and deliver the exact bytes."""
        clock = SimClock()
        hub = SimTransport(clock, seed=3)
        a, b = hub.endpoint("a"), hub.endpoint("b")
        hub.set_drop("a", "b", 0.3)
        hub.set_drop("b", "a", 0.3)
        got = {}
        ma = HandoffManager(a, clock=clock, verify=None)
        mb = HandoffManager(b, clock=clock, verify=None,
                            on_complete=lambda s, man, d:
                            got.setdefault("data", d))
        data = _payload(23000, seed=5)
        sender = ma.send("b", data, kind="carve", meta={"epoch": 9})
        for _ in range(600):
            if sender.done:
                break
            clock.advance(0.25)
            for msg in a.poll():
                ma.handle(msg)
            for msg in b.poll():
                mb.handle(msg)
            ma.pump(clock())
            mb.pump(clock())
        assert sender.done
        assert got["data"] == data
        st = mb.receiver.stats
        assert st["completed"] == 1 and st["rejects"] == 0
        # the drop rate forced retransmits — the recovery lane really ran
        assert sender.stats["retx_chunks"] > 0
        ma.prune()
        assert ma.senders == {}

    def test_manager_stats_roll_up_both_halves(self):
        clock = SimClock()
        hub = SimTransport(clock, seed=1)
        a, b = hub.endpoint("a"), hub.endpoint("b")
        ma = HandoffManager(a, clock=clock, verify=None)
        mb = HandoffManager(b, clock=clock, verify=None)
        sender = ma.send("b", _payload(1000), kind="carve")
        for _ in range(50):
            if sender.done:
                break
            clock.advance(0.1)
            for msg in a.poll():
                ma.handle(msg)
            for msg in b.poll():
                mb.handle(msg)
            ma.pump(clock())
            mb.pump(clock())
        assert ma.stats()["senders_done"] == 1
        assert mb.stats()["rx_chunks"] >= 1
        assert not ma.handle(type("M", (), {"kind": "beat", "src": "b",
                                            "body": {}})())


# ---------------------------------------------------------------------------
# sim-mode multi-box flow: join -> hydrate -> serve -> host loss
# ---------------------------------------------------------------------------

def _sim_cluster(seed=0, remotes=("bng-r1", "bng-r2"), host="beta"):
    clock = SimClock()
    hub = SimTransport(clock, seed=seed)
    coord = ClusterCoordinator(
        clock=clock, sub_nbuckets=0, slice_size=64,
        space_network=SPACE, space_prefix_len=16,
        fabric_endpoint=hub.endpoint("coordinator"),
        fabric_beat_interval_s=0.5, fabric_suspicion_threshold=3,
        fabric_startup_grace_s=2.0,
        ha_probe_interval_s=0.5, ha_failure_threshold=2,
        ha_failover_delay_s=1.0)
    coord.add_instances(["bng-a"], host="alpha",
                        remotes={r: host for r in remotes})
    members = {r: MemberRuntime(hub.endpoint(r), r, host, clock=clock)
               for r in remotes}
    coord.remote_waiter = lambda: [m.tick(clock())
                                   for m in members.values()]
    return clock, hub, coord, members


def _spin_to_serving(clock, coord, members, max_ticks=200):
    ticks = 0
    while not all(m.state == "serving" for m in members.values()) \
            and ticks < max_ticks:
        clock.advance(0.25)
        for m in members.values():
            m.tick(clock())
        coord.tick()
        ticks += 1
    return ticks


class TestMultiboxSimFlow:
    def test_join_hydrate_serve_then_host_loss_promotes_group(self):
        clock, hub, coord, members = _sim_cluster(seed=4)
        try:
            _spin_to_serving(clock, coord, members)
            assert all(m.state == "serving" for m in members.values())
            # founding carve co-dealt the remote slots: everyone serves
            st = coord.status()
            assert st["members"]["bng-r1"]["serving_remote"]
            assert st["members"]["bng-r2"]["serving_remote"]
            assert coord.handoff.stats()["senders_done"] == 2

            macs = [_mac(300 + i) for i in range(24)]
            leased = dora_with_retries(coord, macs, clock)
            assert len(leased) == 24
            ids = coord.member_ids()
            remote_macs = [m for m in macs
                           if instance_for_mac(m, ids) != "bng-a"]
            assert remote_macs  # the carve really steers off-box
            # the member re-checks the placement law on every frame
            assert sum(m.missteers for m in members.values()) == 0
            assert all(m.batches_served > 0 for m in members.values())

            # whole host gone: every beta link cut in one instant
            hub.partition("coordinator", "bng-r1")
            hub.partition("coordinator", "bng-r2")
            coord.remote_waiter = None
            ticks = 0
            while coord.host_losses == 0 and ticks < 120:
                clock.advance(0.5)
                coord.tick()
                ticks += 1
            assert coord.host_losses == 1
            assert coord._lost_hosts == {"beta"}
            # the HA halves promoted AS A GROUP, not one-by-one races
            assert coord.members["bng-r1"].role == "promoted"
            assert coord.members["bng-r2"].role == "promoted"
            assert not coord.members["bng-r1"].remote
            assert coord.failovers == 2

            # flash crowd: renewals must ACK the ORIGINAL addresses
            out = coord.handle_batch(
                [(k, _renew(m, leased[m], 0x9000 + k))
                 for k, m in enumerate(remote_macs)], now=clock())
            for (_l, rep), m in zip(out, remote_macs):
                assert rep is not None
                p = _reply(rep)
                assert p.msg_type == dhcp_codec.ACK
                assert p.yiaddr == leased[m]
            audit = audit_invariants(bng_cluster=coord)
            assert audit.ok, audit.violations_by_kind()
        finally:
            coord.close()
            for m in members.values():
                m.close()

    def test_host_loss_fires_callback_once_with_member_ids(self):
        clock, hub, coord, members = _sim_cluster(seed=2)
        calls = []
        coord.on_host_loss = lambda h, ids: calls.append((h, ids))
        try:
            _spin_to_serving(clock, coord, members)
            hub.partition("coordinator", "bng-r1")
            hub.partition("coordinator", "bng-r2")
            coord.remote_waiter = None
            for _ in range(120):
                if coord.host_losses:
                    break
                clock.advance(0.5)
                coord.tick()
            assert calls == [("beta", ["bng-r1", "bng-r2"])]
            # a lost host never re-triggers
            for _ in range(10):
                clock.advance(0.5)
                coord.tick()
            assert coord.host_losses == 1 and len(calls) == 1
        finally:
            coord.close()
            for m in members.values():
                m.close()

    def test_single_member_down_is_failover_not_host_loss(self):
        # one process dying on a two-member host is the ISSUE 19 lane:
        # per-member failover, no host_loss trigger
        clock, hub, coord, members = _sim_cluster(seed=6)
        try:
            _spin_to_serving(clock, coord, members)
            hub.partition("coordinator", "bng-r1")
            coord.remote_waiter = lambda: members["bng-r2"].tick(clock())
            for _ in range(120):
                clock.advance(0.5)
                members["bng-r2"].tick(clock())
                coord.tick()
                if coord.members["bng-r1"].role == "promoted":
                    break
            assert coord.members["bng-r1"].role == "promoted"
            assert coord.host_losses == 0
            assert coord.members["bng-r2"].remote  # still serving remote
        finally:
            coord.close()
            for m in members.values():
                m.close()

    def test_scenario_is_byte_deterministic(self):
        from bng_tpu.chaos.runner import canonical_json
        from bng_tpu.chaos.scenarios import cluster_host_loss
        r1 = cluster_host_loss(11)
        r2 = cluster_host_loss(11)
        assert r1["ok"], r1
        assert canonical_json(r1) == canonical_json(r2)


class TestJoinBackoff:
    def test_join_delay_is_deterministic_capped_and_jittered(self):
        from bng_tpu.cluster.member import _join_delay
        a = [_join_delay("bng-r1", k) for k in range(12)]
        b = [_join_delay("bng-r1", k) for k in range(12)]
        assert a == b                       # replayable under a seed
        assert all(d <= 8.0 for d in a)     # capped
        assert _join_delay("bng-r1", 3) != _join_delay("bng-r2", 3)
        for k, d in enumerate(a):
            raw = min(8.0, 0.5 * 2 ** k)
            assert raw * 0.5 <= d <= raw    # jitter window [0.5, 1.0]

    def test_unreachable_coordinator_gives_up_loudly(self):
        clock = SimClock()
        hub = SimTransport(clock, seed=0)
        hub.endpoint("coordinator")  # exists but never answers
        ep = hub.endpoint("bng-r9")
        hub.partition("bng-r9", "coordinator")
        lines = []
        m = MemberRuntime(ep, "bng-r9", "gamma", clock=clock,
                          join_deadline_s=6.0, log=lines.append)
        try:
            for _ in range(100):
                clock.advance(0.25)
                m.tick(clock())
                if m.state == "gave_up":
                    break
            assert m.state == "gave_up"
            assert m.join_retries >= 2      # capped backoff retried
            assert any("GIVING UP" in ln for ln in lines)
            # gave_up is terminal: no more join traffic
            retries = m.join_retries
            clock.advance(30.0)
            m.tick(clock())
            assert m.join_retries == retries
        finally:
            m.close()

    def test_join_retries_ride_the_metrics_lane(self):
        from bng_tpu.control.metrics import BNGMetrics
        clock = SimClock()
        hub = SimTransport(clock, seed=0)
        hub.endpoint("coordinator")
        ep = hub.endpoint("bng-r8")
        hub.partition("bng-r8", "coordinator")
        m = MemberRuntime(ep, "bng-r8", "gamma", clock=clock,
                          join_deadline_s=20.0)
        met = BNGMetrics()
        try:
            for _ in range(40):
                clock.advance(0.5)
                m.tick(clock())
            met.record_member(m.status())
            assert met.fabric_join_retries.value() == m.join_retries > 0
        finally:
            m.close()


# ---------------------------------------------------------------------------
# fleet workers allocate through Nexus (the cleared fleet blocker)
# ---------------------------------------------------------------------------

class TestFleetNexus:
    def _nexus(self):
        from bng_tpu.control.cluster_http import ClusterServer

        class Backend:
            def __init__(self):
                self.ips = {}
                self.next = 10

            def allocate(self, subscriber_id, pool_hint):
                if subscriber_id not in self.ips:
                    self.ips[subscriber_id] = f"10.77.0.{self.next}"
                    self.next += 1
                return self.ips[subscriber_id]

            def lookup(self, sid):
                return self.ips.get(sid)

            def lookup_by_ip(self, ip):
                return None

            def release(self, sid):
                return self.ips.pop(sid, None) is not None

            def pool_info(self):
                return {"pools": []}

        backend = Backend()
        srv = ClusterServer().mount_allocator(backend).start()
        return srv, backend

    def test_worker_allocates_through_nexus(self):
        """A FleetSpec with nexus_url builds a worker-local
        HTTPAllocator: DORA addresses come from the central authority,
        not the local slice (the ISSUE-20 fleet-blocker clearance)."""
        from tests.test_fleet import (SERVER_IP, SERVER_MAC, dora,
                                      mac_of, make_pools)

        from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
        srv, backend = self._nexus()
        pools = make_pools(network="10.77.0.0")
        spec = FleetSpec.from_pool_manager(SERVER_MAC, SERVER_IP, pools)
        spec.nexus_url = srv.url
        spec.nexus_node_id = "mb-test"
        fleet = SlowPathFleet(spec, 1, pools, mode="inline")
        try:
            macs = [mac_of(i) for i in range(4)]
            leased = dora(fleet, macs)
            assert backend.ips, "workers never called Nexus"
            for m, ip in leased.items():
                assert u32_to_ip(ip) == backend.ips[m.hex()]
        finally:
            fleet.close()
            srv.close()

    def test_nexus_down_falls_back_to_local_slice(self):
        from tests.test_fleet import (SERVER_IP, SERVER_MAC, dora,
                                      mac_of, make_pools)

        from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
        pools = make_pools(network="10.77.0.0")
        spec = FleetSpec.from_pool_manager(SERVER_MAC, SERVER_IP, pools)
        # nothing listens here: every allocate raises inside the worker
        # adapter and the local slice answers instead
        spec.nexus_url = "http://127.0.0.1:9"
        fleet = SlowPathFleet(spec, 1, pools, mode="inline")
        try:
            leased = dora(fleet, [mac_of(i) for i in range(4)])
            assert len(leased) == 4
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# metrics: member + handoff + host-loss families
# ---------------------------------------------------------------------------

class TestMultiboxMetrics:
    def test_record_member_routes_handoff_families(self):
        from bng_tpu.control.metrics import BNGMetrics
        m = BNGMetrics()
        m.record_member({
            "join_retries": 3,
            "handoff": {"rx_chunks": 7, "rx_corrupt": 1, "rx_dup": 2,
                        "rx_orphan": 4, "tx_chunks": 5, "retx_chunks": 1,
                        "completed": 1, "rejects": 6, "resumes": 2}})
        assert m.fabric_join_retries.value() == 3
        assert m.handoff_chunks.value(disposition="rx") == 7
        assert m.handoff_chunks.value(disposition="corrupt") == 1
        assert m.handoff_chunks.value(disposition="dup") == 2
        assert m.handoff_chunks.value(disposition="orphan") == 4
        assert m.handoff_chunks.value(disposition="tx") == 5
        assert m.handoff_chunks.value(disposition="retx") == 1
        assert m.handoff_transfers.value(outcome="completed") == 1
        assert m.handoff_transfers.value(outcome="rejected") == 6
        assert m.handoff_transfers.value(outcome="resumed") == 2

    def test_record_cluster_carries_host_losses_and_handoff(self):
        from bng_tpu.control.metrics import BNGMetrics
        m = BNGMetrics()
        m.record_cluster({
            "members": {}, "recarves": 0, "failovers": 2,
            "shed_frames": 0, "refused_removes": 0, "host_losses": 1,
            "fabric": {"beats_tx": 1, "beats_rx": 2, "peers": {},
                       "verdicts": {}, "partitions": 0,
                       "handoff": {"tx_chunks": 9, "completed": 2}}})
        assert m.cluster_host_losses.value() == 1
        assert m.handoff_chunks.value(disposition="tx") == 9
        assert m.handoff_transfers.value(outcome="completed") == 2

    def test_scrape_names_are_prometheus_conventional(self):
        from bng_tpu.control.metrics import BNGMetrics
        m = BNGMetrics()
        m.record_member({"join_retries": 1,
                         "handoff": {"rx_chunks": 1, "completed": 1}})
        text = m.registry.expose()
        assert "bng_fabric_join_retries_total" in text
        assert "bng_handoff_chunks_total" in text
        assert "bng_handoff_transfers_total" in text


# ---------------------------------------------------------------------------
# two-process loopback e2e (slow tier): real UDP, real SIGKILL
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestTwoProcessLoopback:
    def test_join_serve_sigkill_host_group(self, tmp_path):
        """The acceptance flow end to end over 127.0.0.1: two real
        `bng cluster run --join` subprocesses hydrate their carve over
        the UDP handoff stream and serve steered DORAs (missteers 0),
        then the whole "host" (both processes) is SIGKILLed — the
        surviving side promotes the HA halves as a group, renewals ACK
        the original addresses, the accounting spool replays exactly
        once, and the cluster audit stays clean."""
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.control.radius.accounting import AccountingManager
        from bng_tpu.control.radius.client import (RadiusClient,
                                                   RadiusServerConfig)
        from bng_tpu.control.radius.packet import RadiusPacket

        coord = ClusterCoordinator(
            sub_nbuckets=0, slice_size=64,
            space_network=SPACE, space_prefix_len=16,
            fabric=True, fabric_bind=("127.0.0.1", 0),
            fabric_beat_interval_s=0.2, fabric_suspicion_threshold=3,
            fabric_startup_grace_s=2.0,
            ha_probe_interval_s=0.2, ha_failure_threshold=2,
            ha_failover_delay_s=0.5)
        procs = []
        logs = {}
        try:
            port = coord.fabric_transport.addr[1]
            coord.add_instances(["bng-a"], host="alpha",
                                remotes={"bng-r1": "beta",
                                         "bng-r2": "beta"})
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            for rid in ("bng-r1", "bng-r2"):
                log = open(tmp_path / f"{rid}.log", "w")
                logs[rid] = log
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "bng_tpu.cli", "cluster",
                     "run", "--join", f"127.0.0.1:{port}",
                     "--node-id", rid, "--join-deadline", "90",
                     "--status-file", str(tmp_path / f"{rid}.json")],
                    stdout=log, stderr=log, env=env))
            deadline = time.time() + 90
            while time.time() < deadline:
                coord.tick()
                st = coord.status()["members"]
                if all(st[r].get("serving_remote")
                       and coord.members[r].instance is not None
                       for r in ("bng-r1", "bng-r2")):
                    break
                time.sleep(0.05)
            assert coord.members["bng-r1"].instance is not None, \
                "bng-r1 never hydrated"
            assert coord.members["bng-r2"].instance is not None, \
                "bng-r2 never hydrated"

            macs = [_mac(700 + i) for i in range(24)]

            class _WallClock:
                """SimClock surface over wall time (dora_with_retries
                advances between retry rounds)."""

                def __call__(self):
                    return time.time()

                def advance(self, _dt):
                    time.sleep(0.05)

            leased = dora_with_retries(coord, macs, _WallClock(),
                                       rounds=8)
            assert len(leased) == 24
            ids = coord.member_ids()
            remote_macs = [m for m in macs
                           if instance_for_mac(m, ids) != "bng-a"]
            assert remote_macs

            # the members' own view: serving, zero missteers
            time.sleep(1.2)  # let a --status-file refresh land
            coord.tick()
            for rid in ("bng-r1", "bng-r2"):
                mst = json.loads(
                    (tmp_path / f"{rid}.json").read_text())
                assert mst["state"] == "serving"
                assert mst["missteers"] == 0
                assert mst["handoff"]["completed"] >= 1

            # the lost box's accounting spool (dark RADIUS: stops spool)
            spool = str(tmp_path / "beta.spool")
            clk = time.time
            dead = AccountingManager(
                RadiusClient([RadiusServerConfig(
                    "10.0.0.5", secret=b"mb-secret", timeout_s=0.05,
                    retries=1)], transport=lambda *a: None, clock=clk),
                interim_interval_s=60, spool_path=spool, clock=clk)
            for i, m in enumerate(remote_macs[:3]):
                sid = f"s-{m.hex()}"
                dead.start(sid, f"sub-{i}", leased[m])
                dead.stop(sid)
            spooled = len(dead.pending)
            assert spooled == 6  # start + stop per session

            stops = []

            def live_transport(data, host, hport, timeout):
                req = RadiusPacket.decode(data)
                if req.get_int(rp.ACCT_STATUS_TYPE) == rp.ACCT_STOP:
                    stops.append(req.id)
                return RadiusPacket(rp.ACCOUNTING_RESPONSE,
                                    req.id).encode(
                    b"mb-secret", request_auth=req.authenticator)

            replays = []

            def on_loss(host, ids_):
                survivor = AccountingManager(
                    RadiusClient([RadiusServerConfig(
                        "10.0.0.5", secret=b"mb-secret", timeout_s=0.5,
                        retries=1)], transport=live_transport,
                        clock=clk),
                    interim_interval_s=60, spool_path=spool, clock=clk)
                replays.append(survivor.retry_tick())
                replays.append(survivor.retry_tick())

            coord.on_host_loss = on_loss

            # SIGKILL the whole host group — the box died mid-flight
            for p in procs:
                p.send_signal(signal.SIGKILL)
            for p in procs:
                p.wait(timeout=10)
            deadline = time.time() + 60
            while coord.host_losses == 0 and time.time() < deadline:
                coord.tick()
                time.sleep(0.05)
            assert coord.host_losses == 1
            assert coord.members["bng-r1"].role == "promoted"
            assert coord.members["bng-r2"].role == "promoted"
            assert replays == [spooled, 0]   # exactly-once replay
            assert len(stops) == 3

            # flash crowd: renewals ACK the ORIGINAL addresses from the
            # promoted surviving-host halves
            out = coord.handle_batch(
                [(k, _renew(m, leased[m], 0xA000 + k))
                 for k, m in enumerate(remote_macs)], now=time.time())
            for (_l, rep), m in zip(out, remote_macs):
                assert rep is not None
                p = _reply(rep)
                assert p.msg_type == dhcp_codec.ACK
                assert p.yiaddr == leased[m]

            audit = audit_invariants(bng_cluster=coord)
            assert audit.ok, audit.violations_by_kind()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
            for log in logs.values():
                log.close()
            coord.close()
