"""Tests for lawful intercept, audit pipeline, and metrics registry."""

import pytest

from bng_tpu.control.audit import (
    AuditLogger, AuditQuery, Event, EventType, IPFIXAuditExporter,
    JSONAuditExporter, LegalHold, MemoryStorage, RetentionManager,
    RotatingFileExporter, Severity, SyslogAuditExporter, event_category,
    standard_retention_policies,
)
from bng_tpu.control.intercept import (
    DeliveryMethod, Direction, ETSIExporter, IRIEventType, InterceptManager,
    JSONExporter, SyslogExporter, Warrant, WarrantStatus, WarrantType,
    parse_etsi_pdu,
)
from bng_tpu.control.metrics import BNGMetrics, MetricsCollector, Registry


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _warrant(clk, **kw):
    base = dict(id="w1", liid="LIID-001", target_subscriber_id="sub-1",
                valid_from=clk.t - 10, valid_until=clk.t + 3600,
                delivery_method=DeliveryMethod.ETSI)
    base.update(kw)
    return Warrant(**base)


# ------------------------------------------------------------ intercept

class TestInterceptManager:
    def test_warrant_validation(self):
        m = InterceptManager()
        with pytest.raises(ValueError):
            m.add_warrant(Warrant(id="w", liid="L"))  # no target
        with pytest.raises(ValueError):
            m.add_warrant(Warrant(id="w", liid="L", target_mac="02:00:00:00:00:01",
                                  valid_from=100, valid_until=50))

    def test_match_by_each_identifier(self):
        clk = FakeClock()
        m = InterceptManager(clock=clk)
        m.add_warrant(_warrant(clk))
        m.add_warrant(_warrant(clk, id="w2", liid="LIID-002",
                               target_subscriber_id="",
                               target_mac="02:AA:BB:CC:DD:01"))
        m.add_warrant(_warrant(clk, id="w3", liid="LIID-003",
                               target_subscriber_id="", target_ipv4="10.0.0.5"))
        assert [w.id for w in m.match_session(subscriber_id="sub-1")] == ["w1"]
        assert [w.id for w in m.match_session(mac="02:aa:bb:cc:dd:01")] == ["w2"]
        assert [w.id for w in m.match_session(ipv4="10.0.0.5")] == ["w3"]
        # one session matching several warrants
        hits = m.match_session(subscriber_id="sub-1", ipv4="10.0.0.5")
        assert {w.id for w in hits} == {"w1", "w3"}

    def test_expired_warrant_does_not_match(self):
        clk = FakeClock()
        m = InterceptManager(clock=clk)
        m.add_warrant(_warrant(clk))
        clk.advance(7200)
        assert m.match_session(subscriber_id="sub-1") == []
        assert m.expire_warrants() == 1
        assert m.get_warrant("w1").status == WarrantStatus.EXPIRED

    def test_suspended_warrant_does_not_match(self):
        clk = FakeClock()
        m = InterceptManager(clock=clk)
        m.add_warrant(_warrant(clk))
        m.update_warrant_status("w1", WarrantStatus.SUSPENDED)
        assert m.match_session(subscriber_id="sub-1") == []

    def test_iri_cc_pipeline_with_etsi_export(self):
        clk = FakeClock()
        pdus = []
        m = InterceptManager(clock=clk)
        m.add_exporter(DeliveryMethod.ETSI, ETSIExporter(pdus.append, "GB"))
        w = _warrant(clk)
        m.add_warrant(w)
        s = m.start_intercept_session(w, "sess-1", subscriber_id="sub-1",
                                      mac="02:aa:bb:cc:dd:01", ipv4="10.0.0.5")
        assert m.record_cc(w, s, Direction.UPSTREAM, "10.0.0.5", "93.184.216.34",
                           40000, 443, 6, b"\x16\x03\x01")
        m.stop_intercept_session("sess-1")

        assert len(pdus) == 3  # IRI start, CC, IRI stop
        start = parse_etsi_pdu(pdus[0])
        assert start["handover"] == ETSIExporter.HI2
        assert start["liid"] == "LIID-001" and start["seq"] == 0
        assert start["iri"]["event_type"] == IRIEventType.SESSION_START.value
        cc = parse_etsi_pdu(pdus[1])
        assert cc["handover"] == ETSIExporter.HI3 and cc["seq"] == 1
        assert cc["source_ip"] == "10.0.0.5" and cc["dest_port"] == 443
        assert cc["payload"] == b"\x16\x03\x01"
        stop = parse_etsi_pdu(pdus[2])
        assert stop["iri"]["event_type"] == IRIEventType.SESSION_STOP.value
        assert w.bytes_intercepted == 3

    def test_cc_filters(self):
        clk = FakeClock()
        m = InterceptManager(clock=clk)
        w = _warrant(clk, filter_dest_ports=[443], filter_protocols=[6])
        m.add_warrant(w)
        s = m.start_intercept_session(w, "sess-1", subscriber_id="sub-1")
        assert m.record_cc(w, s, Direction.UPSTREAM, "10.0.0.5", "1.2.3.4",
                           1111, 443, 6, b"x")
        assert not m.record_cc(w, s, Direction.UPSTREAM, "10.0.0.5", "1.2.3.4",
                               1111, 80, 6, b"x")
        assert not m.record_cc(w, s, Direction.UPSTREAM, "10.0.0.5", "1.2.3.4",
                               1111, 443, 17, b"x")
        assert m.stats()["filtered"] == 2

    def test_remove_warrant_drops_sessions(self):
        clk = FakeClock()
        m = InterceptManager(clock=clk)
        w = _warrant(clk)
        m.add_warrant(w)
        m.start_intercept_session(w, "sess-1")
        m.remove_warrant("w1")
        assert m.get_session("sess-1") is None
        assert m.list_warrants() == []

    def test_json_and_syslog_exporters(self):
        clk = FakeClock()
        out_json, out_syslog = [], []
        m = InterceptManager(clock=clk)
        m.add_exporter(DeliveryMethod.JSON_HTTPS, JSONExporter(out_json.append))
        m.add_exporter(DeliveryMethod.SYSLOG, SyslogExporter(out_syslog.append))
        wj = _warrant(clk, delivery_method=DeliveryMethod.JSON_HTTPS)
        m.add_warrant(wj)
        ws = _warrant(clk, id="w2", liid="LIID-002",
                      delivery_method=DeliveryMethod.SYSLOG)
        m.add_warrant(ws)
        sj = m.start_intercept_session(wj, "sess-j", subscriber_id="sub-1")
        m.start_intercept_session(ws, "sess-s", subscriber_id="sub-1")
        m.record_cc(wj, sj, Direction.DOWNSTREAM, "1.2.3.4", "10.0.0.5",
                    443, 40000, 6, b"abc")
        import json as _json
        lines = [_json.loads(x) for x in out_json]
        assert lines[0]["record_type"] == "IRI"
        assert lines[1]["record_type"] == "CC" and lines[1]["payload_hex"] == "616263"
        assert b"LIID-002" in out_syslog[0]
        # syslog CC delivery is refused -> export_errors counted
        ss = m.get_session("sess-s")
        m.record_cc(ws, ss, Direction.UPSTREAM, "a", "b", 1, 2, 6, b"x")
        assert m.stats()["export_errors"] == 1


# ---------------------------------------------------------------- audit

class TestAudit:
    def test_severity_filter_and_storage(self):
        clk = FakeClock()
        log = AuditLogger(min_severity=Severity.INFO, clock=clk, async_mode=False)
        log.log(EventType.SESSION_START, subscriber_id="s1", mac="02:00:00:00:00:01")
        log.log(EventType.SYSTEM_ERROR, Severity.DEBUG)  # filtered out
        assert log.storage.count() == 1
        assert log.stats["filtered"] == 1

    def test_query(self):
        clk = FakeClock()
        log = AuditLogger(clock=clk, async_mode=False)
        log.log(EventType.AUTH_SUCCESS, subscriber_id="s1", username="alice")
        clk.advance(100)
        log.log(EventType.AUTH_FAILURE, Severity.WARNING, subscriber_id="s2")
        got = log.storage.query(AuditQuery(event_types=[EventType.AUTH_FAILURE]))
        assert len(got) == 1 and got[0].subscriber_id == "s2"
        got = log.storage.query(AuditQuery(start_time=1050.0))
        assert len(got) == 1
        got = log.storage.query(AuditQuery(min_severity=Severity.WARNING))
        assert len(got) == 1

    def test_async_worker_drains(self):
        log = AuditLogger(async_mode=True)
        log.start()
        for _ in range(50):
            log.log(EventType.DHCP_ACK, ip="10.0.0.1")
        log.stop()
        assert log.storage.count() == 50

    def test_helper_entry_points(self):
        log = AuditLogger(async_mode=False)
        log.log_auth(False, username="bob")
        log.log_suspicious("dhcp_starvation", 80, mac="02:00:00:00:00:09")
        log.log_nat_mapping(ip="100.64.0.5", nat_public_ip="203.0.113.1",
                            nat_public_port=4096, protocol=6)
        evs = log.storage.query(AuditQuery())
        assert evs[0].event_type == EventType.AUTH_FAILURE
        assert evs[1].details["threat_type"] == "dhcp_starvation"
        assert evs[2].category == "nat"

    def test_event_category(self):
        assert event_category(EventType.DHCP_ACK) == "dhcp"
        assert event_category(EventType.WALLED_GARDEN_ADD) == "walledgarden"
        assert event_category(EventType.BRUTE_FORCE_DETECTED) == "security"
        assert event_category(EventType.API_RATE_LIMITED) == "api"

    def test_syslog_exporter_format(self):
        lines = []
        log = AuditLogger(async_mode=False, clock=FakeClock(1700000000.0))
        log.add_exporter(SyslogAuditExporter(lines.append))
        log.log(EventType.SESSION_START, subscriber_id="s1", message="up")
        text = lines[0].decode()
        assert text.startswith("<") and 'type="SESSION_START"' in text
        assert 'subscriber="s1"' in text and text.endswith("up")

    def test_ipfix_exporter_binary_record(self):
        from bng_tpu.utils.net import fnv1a32
        recs = []
        log = AuditLogger(async_mode=False, clock=FakeClock(1700000000.0))
        log.add_exporter(IPFIXAuditExporter(recs.append))
        log.log(EventType.SESSION_START)  # not a NAT event -> skipped
        log.log_nat_mapping(ip="100.64.0.5", nat_private_port=5555,
                            nat_public_ip="203.0.113.1", nat_public_port=4096,
                            protocol=6, subscriber_id="s1")
        assert len(recs) == 1 and len(recs[0]) == IPFIXAuditExporter.RECORD.size
        ts, priv, pport, pub, pubport, proto, ev, subhash, _ = \
            IPFIXAuditExporter.RECORD.unpack(recs[0])
        assert ts == 1700000000000 and pport == 5555 and pubport == 4096
        assert proto == 6 and ev == 1 and subhash == fnv1a32(b"s1")

    def test_rotating_file_exporter(self, tmp_path):
        path = str(tmp_path / "audit.log")
        exp = RotatingFileExporter(path, max_bytes=200, max_files=2)
        log = AuditLogger(async_mode=False)
        log.add_exporter(exp)
        for i in range(20):
            log.log(EventType.CONFIG_CHANGE, message=f"change {i}")
        exp.close()
        files = list(tmp_path.iterdir())
        gz = [f for f in files if f.suffix == ".gz"]
        assert gz, "rotation should gzip old files"
        assert len(gz) <= 2, "retention should cap rotated files"

    def test_retention_with_legal_hold(self):
        clk = FakeClock(1_000_000_000.0)
        storage = MemoryStorage()
        log = AuditLogger(storage=storage, clock=clk, async_mode=False)
        log.log(EventType.DHCP_ACK, subscriber_id="keep-me")
        log.log(EventType.DHCP_ACK, subscriber_id="drop-me")
        rm = RetentionManager(clock=clk)
        rm.add_legal_hold(LegalHold(id="h1", subscriber_id="keep-me"))
        clk.advance(91 * 86400)  # dhcp retention is 90 days
        dropped = rm.enforce(storage)
        assert dropped == 1
        left = storage.query(AuditQuery())
        assert len(left) == 1 and left[0].subscriber_id == "keep-me"

    def test_expired_hold_releases_events(self):
        clk = FakeClock(1_000_000_000.0)
        rm = RetentionManager(clock=clk)
        rm.add_legal_hold(LegalHold(id="h1", subscriber_id="s",
                                    expires_at=clk.t + 10))
        e = Event(event_type=EventType.DHCP_ACK, subscriber_id="s",
                  timestamp=clk.t)
        assert rm.is_under_legal_hold(e)
        clk.advance(11)
        assert not rm.is_under_legal_hold(e)
        assert rm.cleanup_expired_holds() == 1

    def test_standard_policies(self):
        p = standard_retention_policies()
        assert p["nat"] == 365 and p["admin"] == 730 and p["system"] == 30


# -------------------------------------------------------------- metrics

class TestMetrics:
    def test_counter_gauge_exposition(self):
        r = Registry()
        c = r.counter("bng_test_total", "test counter", ("type",))
        g = r.gauge("bng_test_gauge", "test gauge")
        c.inc(type="a")
        c.inc(2, type="b")
        g.set(7)
        text = r.expose()
        assert 'bng_test_total{type="a"} 1' in text
        assert 'bng_test_total{type="b"} 2' in text
        assert "bng_test_gauge 7" in text
        assert "# TYPE bng_test_total counter" in text

    def test_histogram(self):
        r = Registry()
        h = r.histogram("bng_lat_seconds", "latency", buckets=(0.001, 0.01, 0.1))
        h.observe(0.005)
        h.observe(0.05)
        text = r.expose()
        assert 'bng_lat_seconds_bucket{le="0.01"} 1' in text
        assert 'bng_lat_seconds_bucket{le="+Inf"} 2' in text
        assert "bng_lat_seconds_count 2" in text

    def test_duplicate_name_rejected(self):
        r = Registry()
        r.counter("x_total", "x")
        with pytest.raises(ValueError):
            r.counter("x_total", "x")

    def test_bng_families_name_parity(self):
        m = BNGMetrics()
        text = m.expose()
        for name in ("bng_dhcp_requests_total", "bng_dhcp_cache_hit_rate",
                     "bng_ebpf_fastpath_hits_total", "bng_ebpf_fastpath_misses_total",
                     "bng_pool_utilization_ratio", "bng_session_active",
                     "bng_nat_bindings_active", "bng_radius_requests_total",
                     "bng_qos_policies_active", "bng_pppoe_sessions_active",
                     "bng_bgp_peers_up", "bng_circuit_id_hash_collisions_total"):
            assert name in text, name

    def test_collect_engine_stats(self):
        import numpy as np
        from bng_tpu.runtime.engine import EngineStats
        m = BNGMetrics()
        st = EngineStats()
        st.dhcp = np.array([100, 80, 20, 75, 5, 1, 2, 0, 1, 20], dtype=np.uint64)
        m.collect_engine(st)
        assert m.ebpf_fastpath_hits.value() == 80
        assert m.dhcp_cache_hit_rate.value() == 0.8

    def test_collect_pools(self):
        m = BNGMetrics()
        m.collect_pools({"res-a": {"size": 100, "allocated": 25}})
        assert m.pool_utilization.value(pool="res-a") == 0.25
        assert m.pool_available.value(pool="res-a") == 75

    def test_http_endpoint(self):
        import urllib.request
        m = BNGMetrics()
        col = MetricsCollector(m, interval=60)
        port = col.serve_http(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
            assert "bng_dhcp_requests_total" in body
            assert urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).status == 200
        finally:
            col.stop()

    def test_collector_sources(self):
        m = BNGMetrics()
        col = MetricsCollector(m, interval=60)
        col.add_source(lambda: m.subscriber_total.set(42))
        col.collect_once()
        assert m.subscriber_total.value() == 42


class TestRound4Metrics:
    def test_garden_and_dns_families_exposed(self):
        from types import SimpleNamespace

        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        m.collect_garden(SimpleNamespace(garden=[7, 3]))
        m.collect_dns({"served": 10, "bad_packets": 1, "server_errors": 0,
                       "overloaded": 2},
                      {"queries": 20, "cache_hits": 5})
        text = m.expose()
        assert "bng_walled_garden_device_drops_total 7" in text
        assert "bng_walled_garden_device_allowed_total 3" in text
        assert 'bng_dns_queries_total{outcome="served"} 10' in text
        assert "bng_dns_overloaded_total 2" in text
        assert "bng_dns_cache_hit_rate 0.25" in text

    def test_cli_collects_round4_sources(self):
        from bng_tpu.cli import BNGApp, BNGConfig

        app = BNGApp(BNGConfig(dns_enabled=True, dns_listen="127.0.0.1:0"))
        try:
            app.components["collector"].collect_once()
            text = app.components["metrics"].expose()
            assert "bng_walled_garden_device_drops_total" in text
            assert "bng_dns_queries_total" in text
        finally:
            app.close()
