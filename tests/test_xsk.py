"""AF_XDP attach ladder: compiles everywhere, falls back cleanly.

The container has no NIC queues or CAP_NET_RAW, so these tests exercise
exactly what the reference's loader tests exercise on dev boxes: the
LADDER (driver -> generic -> stub), not a live NIC (pkg/ebpf
loader.go:294-315 role).
"""

import pytest

from bng_tpu.runtime import xsk
from bng_tpu.runtime.ring import NativeRing, PyRing, load_native


needs_native = pytest.mark.skipif(load_native() is None,
                                  reason="no C++ toolchain")


class TestLadder:
    @needs_native
    def test_probe_reports_a_rung(self):
        assert xsk.probe() in (xsk.MODE_COPY, xsk.MODE_MEMORY)

    def test_no_interface_is_memory_rung(self):
        ring = PyRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="")
        assert att.mode == xsk.MODE_MEMORY and att.xsk is None

    @needs_native
    def test_nonexistent_interface_falls_back(self):
        ring = NativeRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="bng-does-not-exist0")
        assert att.mode == xsk.MODE_MEMORY and att.xsk is None
        assert "failed" in att.detail
        ring.close()

    @needs_native
    def test_pyring_has_no_umem_rung(self):
        att = xsk.open_wire(PyRing(nframes=64, frame_size=256, depth=32),
                            ifname="lo")
        assert att.mode == xsk.MODE_MEMORY and "UMEM" in att.detail

    @needs_native
    def test_real_interface_ladder_never_crashes(self):
        """On 'lo': either a rung binds (privileged kernel) or the ladder
        lands on memory with a diagnostic — both are contract-conforming."""
        ring = NativeRing(nframes=64, frame_size=2048, depth=32)
        att = xsk.open_wire(ring, ifname="lo")
        assert att.mode in (xsk.MODE_ZEROCOPY, xsk.MODE_COPY, xsk.MODE_MEMORY)
        if att.xsk is not None:
            assert att.xsk.fd >= 0
            att.xsk.close()
        ring.close()

    def test_memory_rung_ring_still_serves(self):
        """The stub rung is not a dead end: the in-memory ring keeps the
        full assemble/complete API (what the engine actually consumes)."""
        import numpy as np

        ring = PyRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="")
        assert att.mode == xsk.MODE_MEMORY
        ring.rx_push(b"\x02" * 60)
        out = np.zeros((4, 256), dtype=np.uint8)
        ln = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)
        assert ring.assemble(out, ln, fl) == 1
