"""AF_XDP attach ladder: compiles everywhere, falls back cleanly.

The container has no NIC queues or CAP_NET_RAW, so these tests exercise
exactly what the reference's loader tests exercise on dev boxes: the
LADDER (driver -> generic -> stub), not a live NIC (pkg/ebpf
loader.go:294-315 role).
"""

import time

import numpy as np
import pytest

from bng_tpu.runtime import xsk
from bng_tpu.runtime.ring import NativeRing, PyRing, load_native


needs_native = pytest.mark.skipif(load_native() is None,
                                  reason="no C++ toolchain")


class TestLadder:
    @needs_native
    def test_probe_reports_a_rung(self):
        assert xsk.probe() in (xsk.MODE_COPY, xsk.MODE_MEMORY)

    def test_no_interface_is_memory_rung(self):
        ring = PyRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="")
        assert att.mode == xsk.MODE_MEMORY and att.xsk is None

    @needs_native
    def test_nonexistent_interface_falls_back(self):
        ring = NativeRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="bng-does-not-exist0")
        assert att.mode == xsk.MODE_MEMORY and att.xsk is None
        assert "failed" in att.detail
        ring.close()

    @needs_native
    def test_pyring_has_no_umem_rung(self):
        att = xsk.open_wire(PyRing(nframes=64, frame_size=256, depth=32),
                            ifname="lo")
        assert att.mode == xsk.MODE_MEMORY and "UMEM" in att.detail

    @needs_native
    def test_real_interface_ladder_never_crashes(self):
        """On 'lo': either a rung binds (privileged kernel) or the ladder
        lands on memory with a diagnostic — both are contract-conforming."""
        ring = NativeRing(nframes=64, frame_size=2048, depth=32)
        att = xsk.open_wire(ring, ifname="lo")
        assert att.mode in (xsk.MODE_ZEROCOPY, xsk.MODE_COPY, xsk.MODE_MEMORY)
        if att.xsk is not None:
            assert att.xsk.fd >= 0
            att.xsk.close()
        ring.close()

    def test_memory_rung_ring_still_serves(self):
        """The stub rung is not a dead end: the in-memory ring keeps the
        full assemble/complete API (what the engine actually consumes)."""
        import numpy as np

        ring = PyRing(nframes=64, frame_size=256, depth=32)
        att = xsk.open_wire(ring, ifname="")
        assert att.mode == xsk.MODE_MEMORY
        ring.rx_push(b"\x02" * 60)
        out = np.zeros((4, 256), dtype=np.uint8)
        ln = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)
        assert ring.assemble(out, ln, fl) == 1


def _veth_ok() -> bool:
    import subprocess

    r = subprocess.run(["ip", "link", "add", "bngxt0", "type", "veth",
                        "peer", "name", "bngxt1"], capture_output=True)
    if r.returncode != 0:
        return False
    subprocess.run(["ip", "link", "del", "bngxt0"], capture_output=True)
    return True


def _rung1_possible() -> bool:
    from bng_tpu.runtime import xdp_redirect, xsk

    return (xsk.probe() != "unavailable" and xdp_redirect.probe()
            and _veth_ok())


@pytest.mark.skipif(not _rung1_possible(),
                    reason="needs CAP_NET_ADMIN + AF_XDP + CAP_BPF")
class TestCopyModeRungOnVeth:
    """The ladder's real rung 1 against the real kernel (VERDICT r3 item
    7; the reference's kernel-verifier CI gate role,
    .github/workflows/bpf-test.yml): copy-mode bind on a veth pair, the
    xskmap-redirect program through the ACTUAL BPF verifier, one frame
    kernel->UMEM->ring->verdict->kernel."""

    IF_A, IF_B = "bngxt0", "bngxt1"

    @pytest.fixture
    def veth(self):
        import subprocess

        subprocess.run(["ip", "link", "del", self.IF_A], capture_output=True)
        subprocess.run(["ip", "link", "add", self.IF_A, "type", "veth",
                        "peer", "name", self.IF_B], check=True,
                       capture_output=True)
        for i in (self.IF_A, self.IF_B):
            subprocess.run(["ip", "link", "set", i, "up"], check=True,
                           capture_output=True)
        time.sleep(0.3)  # carrier settle
        yield
        subprocess.run(["ip", "link", "del", self.IF_A], capture_output=True)

    def test_rung1_full_loop(self, veth):
        import socket as so

        from bng_tpu.control import packets
        from bng_tpu.runtime import xdp_redirect
        from bng_tpu.runtime.ring import NativeRing

        ring = NativeRing(nframes=4096, frame_size=2048, depth=1024)
        att = xsk.open_wire(ring, ifname=self.IF_A, queue=0)
        assert att.mode == "copy", (att.mode, att.detail)  # rung 1 reached
        s = att.xsk
        redir = xdp_redirect.XdpRedirect(self.IF_A, {0: s.fd})
        tx = so.socket(so.AF_PACKET, so.SOCK_RAW)
        rx_sock = so.socket(so.AF_PACKET, so.SOCK_RAW, so.htons(0x0003))
        try:
            s.pump()  # pre-fill the kernel fill ring
            frame = packets.udp_packet(
                b"\x02\xaa\xaa\xaa\xaa\x01", b"\x02\xbb\xbb\xbb\xbb\x02",
                0x0A000001, 0x0A000002, 5000, 6000, b"xsk-rung-one")
            tx.bind((self.IF_B, 0))
            rx_sock.bind((self.IF_B, 0))
            rx_sock.settimeout(0.1)
            tx.send(frame)

            pkt = np.zeros((8, 2048), dtype=np.uint8)
            ln = np.zeros((8,), dtype=np.uint32)
            fl = np.zeros((8,), dtype=np.uint32)
            n = 0
            for _ in range(100):  # noise (IPv6 ND etc.) may share the veth
                s.pump()
                if ring.rx_pending():
                    n = ring.assemble(pkt, ln, fl)
                    rows = [bytes(pkt[i, : ln[i]]) for i in range(n)]
                    if frame in rows:
                        break
                    ring.complete(np.full((n,), 1, dtype=np.uint8), pkt,
                                  ln, n)
                    n = 0
                time.sleep(0.02)
            assert n, "frame never arrived through the kernel"
            rows = [bytes(pkt[i, : ln[i]]) for i in range(n)]
            idx = rows.index(frame)
            assert fl[idx] & 0x1  # from_access

            # verdict TX with a device 'rewrite'; must egress via kernel
            reply = bytearray(frame)
            reply[-1] ^= 0xFF
            pkt[idx, : len(reply)] = np.frombuffer(bytes(reply),
                                                   dtype=np.uint8)
            ln[idx] = len(reply)
            verdict = np.full((n,), 1, dtype=np.uint8)
            verdict[idx] = 2  # TX
            ring.complete(verdict, pkt, ln, n)
            got = None
            deadline = time.time() + 3
            while time.time() < deadline:
                s.pump()
                try:
                    data = rx_sock.recv(4096)
                except TimeoutError:
                    continue
                if data == bytes(reply):
                    got = data
                    break
            assert got == bytes(reply), s.pump_stats
            assert s.pump_stats["completed"] >= 1  # kernel reported the TX
            assert ring.free_frames() > 0
        finally:
            tx.close()
            rx_sock.close()
            redir.close()
            s.close()
            ring.close()

    def test_verifier_rejects_bad_program(self, veth):
        """The kernel verifier is real: an out-of-bounds ctx read must be
        rejected (proves the gate actually gates)."""
        import struct

        from bng_tpu.runtime import xdp_redirect as xr

        bad = b"".join([
            xr._insn(0x61, 2, 1, 4096, 0),  # r2 = ctx[4096]: out of range
            xr._insn(0xB7, 0, 0, 0, 2),
            xr._insn(0x95, 0, 0, 0, 0),
        ])
        lic = __import__("ctypes").create_string_buffer(b"GPL")
        ib = __import__("ctypes").create_string_buffer(bad, len(bad))
        attr = struct.pack(
            "<IIQQIIQII16sII", xr.BPF_PROG_TYPE_XDP, len(bad) // 8,
            __import__("ctypes").addressof(ib),
            __import__("ctypes").addressof(lic),
            0, 0, 0, 0, 0, b"bng_bad", 0, xr.BPF_XDP).ljust(128, b"\x00")
        with pytest.raises(OSError):
            xr._bpf(xr.BPF_PROG_LOAD, attr)
