"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The reference tests distribution without a cluster via stub backends and
in-process peers (SURVEY.md §4.6); here the analog is
xla_force_host_platform_device_count=8 — real shard_map, real collectives,
no TPU pod needed.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.ops.table import (HostTable, TableGeom, device_lookup,
                               exchange_capacity, lookup, shard_owner)
from bng_tpu.parallel.hashring import (
    hashring_allocate,
    rendezvous_owner,
    rendezvous_ranked,
)
from bng_tpu.parallel.sharded import (AXIS, ShardedCluster, _shard_map,
                                      make_mesh)
from bng_tpu.utils.net import ip_to_u32

N = 4


class TestHashring:
    def test_rendezvous_deterministic_and_balanced(self):
        nodes = [f"node{i}" for i in range(5)]
        owners = [rendezvous_owner(nodes, f"sub-{i}") for i in range(1000)]
        assert owners == [rendezvous_owner(nodes, f"sub-{i}") for i in range(1000)]
        counts = {n: owners.count(n) for n in nodes}
        assert all(c > 100 for c in counts.values()), f"skewed: {counts}"

    def test_rendezvous_failover_minimal_disruption(self):
        """HRW property: removing a node only remaps its own keys."""
        nodes = [f"node{i}" for i in range(5)]
        keys = [f"sub-{i}" for i in range(500)]
        before = {k: rendezvous_owner(nodes, k) for k in keys}
        survivors = nodes[:-1]
        for k in keys:
            after = rendezvous_owner(survivors, k)
            if before[k] != nodes[-1]:
                assert after == before[k]

    def test_ranked_first_is_owner(self):
        nodes = [f"n{i}" for i in range(4)]
        for k in ("a", "b", "c"):
            ranked = rendezvous_ranked(nodes, k)
            assert ranked[0] == rendezvous_owner(nodes, k)
            assert sorted(ranked) == sorted(nodes)

    def test_hashring_allocate_deterministic_probing(self):
        taken = set()
        idx1 = hashring_allocate("sub-A", 256, lambda i: i not in taken)
        assert idx1 is not None
        # same subscriber, same answer (cross-node determinism)
        assert hashring_allocate("sub-A", 256, lambda i: i not in taken) == idx1
        taken.add(idx1)
        idx2 = hashring_allocate("sub-A", 256, lambda i: i not in taken)
        assert idx2 is not None and idx2 != idx1
        full = hashring_allocate("sub-B", 8, lambda i: False)
        assert full is None


class TestShardedLookup:
    def test_matches_local_lookup(self):
        """Sharded all-to-all lookup == N independent local lookups."""
        mesh = make_mesh(N)
        rng = np.random.default_rng(3)
        shards = [HostTable(nbuckets=64, key_words=2, val_words=4) for _ in range(N)]
        keys = rng.integers(0, 2**32, size=(200, 2), dtype=np.uint32)
        keys = np.unique(keys, axis=0)
        for i, k in enumerate(keys):
            words = [k[0:1], k[1:2]]
            o = int(shard_owner(words, N)[0])
            shards[o].insert(k, [i, i + 1, i + 2, i + 3])

        stacked = jax.tree.map(
            lambda *xs: jnp.stack(xs), *[s.device_state() for s in shards]
        )
        g = TableGeom(nbuckets=64, stash=64, axis=AXIS, n_shards=N)

        b = 32
        queries = np.concatenate([
            keys[: b - 8],
            rng.integers(0, 2**32, size=(8, 2), dtype=np.uint32),  # misses
        ])  # one batch per shard -> replicate the same queries on all shards
        qs = np.broadcast_to(queries, (N,) + queries.shape).reshape(N * b, 2).copy()

        def local(tabs1, q):
            tabs = jax.tree.map(lambda x: x[0], tabs1)
            r = lookup(tabs, q, g)
            return r.found, r.vals

        f = jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS)),
        ))
        found, vals = f(jax.tree.map(lambda *xs: jnp.stack(xs), *[s.device_state() for s in shards]),
                        jnp.asarray(qs))
        found = np.asarray(found).reshape(N, b)
        vals = np.asarray(vals).reshape(N, b, 4)
        present = {tuple(k) for k in keys}
        for shard in range(N):
            for i, q in enumerate(queries):
                if tuple(q) in present:
                    assert found[shard, i], f"shard {shard} missed key {q}"
                    ki = np.nonzero((keys == q).all(axis=1))[0][0]
                    assert vals[shard, i].tolist() == [ki, ki + 1, ki + 2, ki + 3]
                else:
                    assert not found[shard, i]


class TestShardedCluster:
    SERVER_MAC = bytes.fromhex("02aabbccdd01")
    SERVER_IP = ip_to_u32("10.0.0.1")
    T0 = 1_753_000_000

    def _discover_frame(self, mac):
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def test_dhcp_answered_from_any_shard(self):
        """A subscriber cached on shard X is answered when its DISCOVER
        lands on any chip — the all-to-all table routing at work."""
        cl = ShardedCluster(N, batch_per_shard=8)
        cl.set_server_config_all(self.SERVER_MAC, self.SERVER_IP)
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, self.SERVER_IP, lease_time=3600)

        macs = [bytes.fromhex(f"02c0ffee00{i:02x}") for i in range(8)]
        owners = []
        for i, mac in enumerate(macs):
            o = cl.add_subscriber(mac, pool_id=1, ip=ip_to_u32(f"10.0.0.{50+i}"),
                                  lease_expiry=self.T0 + 600)
            owners.append(o)
        assert len(set(owners)) > 1, "want subscribers spread over shards"
        cl.sync_tables()

        B = N * cl.b
        pkt = np.zeros((B, 512), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        fa = np.ones((B,), dtype=bool)
        # place each subscriber's DISCOVER on a chip that is NOT its owner
        for i, mac in enumerate(macs):
            chip = (owners[i] + 1) % N
            row = chip * cl.b + (i % cl.b)
            f = self._discover_frame(mac)
            pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
            length[row] = len(f)

        out = cl.step(pkt, length, fa, self.T0, 0)
        verdict = out["verdict"]
        tx_rows = np.nonzero(verdict == 2)[0]
        assert len(tx_rows) == len(macs), f"expected {len(macs)} device replies, got {len(tx_rows)}"
        # check one reply's payload
        row = int(tx_rows[0])
        raw = bytes(np.asarray(out["out_pkt"])[row, : int(out["out_len"][row])])
        d = dhcp_codec.decode(packets.decode(raw).payload)
        assert d.msg_type == dhcp_codec.OFFER
        # psum'd stats: every chip counted its own hits, reduced globally
        from bng_tpu.ops.dhcp import ST_HIT

        assert out["dhcp_stats"][ST_HIT] == len(macs)

    def test_sharded_dhcp_fast_lane_parity(self):
        """The sharded DHCP-only program (dhcp_step) answers cross-shard
        DISCOVERs byte-for-byte like the fused sharded step, shares the
        same table leaves (an update drained through one program is
        visible to the other), and psums its stats."""
        from bng_tpu.ops.dhcp import ST_HIT

        cl = ShardedCluster(N, batch_per_shard=8)
        cl.set_server_config_all(self.SERVER_MAC, self.SERVER_IP)
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, self.SERVER_IP, lease_time=3600)
        mac = bytes.fromhex("02c0ffee0077")
        owner = cl.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.90"),
                                  lease_expiry=self.T0 + 600)
        cl.sync_tables()

        B = N * cl.b
        pkt = np.zeros((B, 512), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        row = ((owner + 1) % N) * cl.b  # land on a non-owner chip
        f = self._discover_frame(mac)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)

        out = cl.dhcp_step(pkt, length, self.T0)
        assert out["is_reply"][row] and out["dhcp_stats"][ST_HIT] == 1
        fast = bytes(np.asarray(out["out_pkt"])[row, : int(out["out_len"][row])])

        out2 = cl.step(pkt, length, np.ones((B,), dtype=bool), self.T0, 0)
        assert out2["verdict"][row] == 2
        fused = bytes(np.asarray(out2["out_pkt"])[row, : int(out2["out_len"][row])])
        assert fast == fused

        # update drained through the DHCP-only program is visible to the
        # fused step (shared, threaded table leaves)
        mac2 = bytes.fromhex("02c0ffee0078")
        cl.add_subscriber(mac2, pool_id=1, ip=ip_to_u32("10.0.0.91"),
                          lease_expiry=self.T0 + 600)
        f2 = self._discover_frame(mac2)
        pkt2 = np.zeros((B, 512), dtype=np.uint8)
        length2 = np.zeros((B,), dtype=np.uint32)
        pkt2[0, : len(f2)] = np.frombuffer(f2, dtype=np.uint8)
        length2[0] = len(f2)
        out3 = cl.dhcp_step(pkt2, length2, self.T0 + 1)
        assert out3["is_reply"][0]
        out4 = cl.step(pkt2, length2, np.ones((B,), dtype=bool), self.T0 + 2, 0)
        assert out4["verdict"][0] == 2

    def test_unknown_subscriber_misses_globally(self):
        cl = ShardedCluster(N, batch_per_shard=8)
        cl.set_server_config_all(self.SERVER_MAC, self.SERVER_IP)
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, self.SERVER_IP)
        cl.sync_tables()
        B = N * cl.b
        pkt = np.zeros((B, 512), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        f = self._discover_frame(bytes.fromhex("02ffffffff01"))
        pkt[0, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[0] = len(f)
        out = cl.step(pkt, length, np.ones((B,), dtype=bool), self.T0, 0)
        assert (out["verdict"] == 2).sum() == 0
        from bng_tpu.ops.dhcp import ST_MISS

        assert out["dhcp_stats"][ST_MISS] == 1

    def test_subscriber_added_after_first_step_reaches_device(self):
        """Control-plane writes after the first step flow through the
        per-step update drain (regression: they used to stay host-only)."""
        cl = ShardedCluster(N, batch_per_shard=8)
        cl.set_server_config_all(self.SERVER_MAC, self.SERVER_IP)
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, self.SERVER_IP)
        B = N * cl.b
        pkt = np.zeros((B, 512), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        fa = np.ones((B,), dtype=bool)
        mac = bytes.fromhex("02c0ffee9999")
        f = self._discover_frame(mac)
        pkt[0, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[0] = len(f)

        # step 1: unknown -> slow path
        out = cl.step(pkt, length, fa, self.T0, 0)
        assert (out["verdict"] == 2).sum() == 0

        # slow path installs the lease AFTER the cluster is live
        cl.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.99"),
                          lease_expiry=self.T0 + 600)

        # step 2: answered on-device
        out = cl.step(pkt, length, fa, self.T0 + 1, 0)
        tx_rows = np.nonzero(out["verdict"] == 2)[0]
        assert len(tx_rows) == 1
        row = int(tx_rows[0])
        raw = bytes(np.asarray(out["out_pkt"])[row, : int(out["out_len"][row])])
        d = dhcp_codec.decode(packets.decode(raw).payload)
        assert d.msg_type == dhcp_codec.OFFER
        assert d.yiaddr == ip_to_u32("10.0.0.99")


class TestShardedExchangeCapacity:
    """Round-1 ask #7: the exchange reserves O(b/N * factor) per
    destination, not the O(b) worst case; overflow lanes punt."""

    def test_balanced_batch_never_punts(self):
        mesh = make_mesh(N)
        rng = np.random.default_rng(11)
        shards = [HostTable(nbuckets=64, key_words=2, val_words=4)
                  for _ in range(N)]
        keys = rng.integers(0, 2**32, size=(400, 2), dtype=np.uint32)
        keys = np.unique(keys, axis=0)[:256]
        for i, k in enumerate(keys):
            o = int(shard_owner([k[0:1], k[1:2]], N)[0])
            shards[o].insert(k, [i, 0, 0, 0])
        g = TableGeom(nbuckets=64, stash=64, axis=AXIS, n_shards=N,
                      capacity_factor=2.0)
        b = 32
        qs = np.broadcast_to(keys[:b], (N, b, 2)).reshape(N * b, 2).copy()

        def local(tabs1, q):
            tabs = jax.tree.map(lambda x: x[0], tabs1)
            r = lookup(tabs, q, g)
            return r.found, r.punted

        f = jax.jit(_shard_map(
            local, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS))))
        found, punted = f(
            jax.tree.map(lambda *xs: jnp.stack(xs),
                         *[s.device_state() for s in shards]),
            jnp.asarray(qs))
        # a hash-balanced batch fits within factor-2 capacity: no punts
        assert not np.asarray(punted).any()
        assert np.asarray(found).all()

    def test_pathological_skew_punts_not_corrupts(self):
        """Every lane targeting ONE shard: capacity C lanes resolve, the
        rest punt (found=False, punted=True) — never wrong values."""
        mesh = make_mesh(N)
        shards = [HostTable(nbuckets=64, key_words=2, val_words=4)
                  for _ in range(N)]
        # craft keys that all hash to the same owner shard
        rng = np.random.default_rng(12)
        same_owner = []
        want = None
        while len(same_owner) < 32:
            k = rng.integers(0, 2**32, size=(2,), dtype=np.uint32)
            o = int(shard_owner([k[0:1], k[1:2]], N)[0])
            if want is None:
                want = o
            if o == want:
                same_owner.append(k)
        keys = np.stack(same_owner)
        for i, k in enumerate(keys):
            shards[want].insert(k, [i, 0, 0, 0])
        g = TableGeom(nbuckets=64, stash=64, axis=AXIS, n_shards=N,
                      capacity_factor=2.0)
        b = 32
        C = exchange_capacity(b, g)
        assert C < b  # the punt path must actually be exercised
        qs = np.broadcast_to(keys, (N, b, 2)).reshape(N * b, 2).copy()

        def local(tabs1, q):
            tabs = jax.tree.map(lambda x: x[0], tabs1)
            r = lookup(tabs, q, g)
            return r.found, r.punted, r.vals[:, 0]

        f = jax.jit(_shard_map(
            local, mesh=mesh, in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS), P(AXIS))))
        found, punted, v0 = f(
            jax.tree.map(lambda *xs: jnp.stack(xs),
                         *[s.device_state() for s in shards]),
            jnp.asarray(qs))
        found = np.asarray(found).reshape(N, b)
        punted = np.asarray(punted).reshape(N, b)
        v0 = np.asarray(v0).reshape(N, b)
        for shard in range(N):
            # first C lanes (arrival order) resolve correctly...
            assert found[shard, :C].all()
            assert not punted[shard, :C].any()
            assert v0[shard, :C].tolist() == list(range(C))
            # ...the overflow punts cleanly
            assert punted[shard, C:].all()
            assert not found[shard, C:].any()

    def test_factor_n_reproduces_worst_case_exchange(self):
        """capacity_factor >= N -> C = b: the exact never-punt exchange."""
        g = TableGeom(nbuckets=64, stash=64, axis=AXIS, n_shards=N,
                      capacity_factor=float(N))
        b = 32
        C = exchange_capacity(b, g)
        assert C == b


class TestSkewDegradesToSlowPath:
    """The punt-safety invariant end-to-end: DISCOVERs beyond one shard's
    exchange capacity become slow-path lanes (the authoritative DHCP
    server's job), never drops or wrong replies."""

    SERVER_MAC = bytes.fromhex("02aabbccdd01")
    SERVER_IP = ip_to_u32("10.0.0.1")
    T0 = 1_753_000_000

    # compile-heavy (~27s unique trace); punt-safety also proven by
    # TestRingShardSteering's wrong-shard punt — slow tier runs this one
    @pytest.mark.slow
    def test_overflowed_discovers_go_slow_not_dropped(self):
        cl = ShardedCluster(N, batch_per_shard=32)
        cl.set_server_config_all(self.SERVER_MAC, self.SERVER_IP)
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, self.SERVER_IP,
                        lease_time=3600)

        # 24 subscribers whose MAC keys ALL hash to one owner shard
        same, owner = [], None
        i = 0
        while len(same) < 24:
            mac = bytes.fromhex(f"02c0ffee{i:04x}")
            o = cl.dhcp_sub_shard(mac)
            if owner is None:
                owner = o
            if o == owner:
                same.append(mac)
            i += 1
        for j, mac in enumerate(same):
            cl.add_subscriber(mac, pool_id=1, ip=ip_to_u32(f"10.0.1.{j + 1}"),
                              lease_expiry=self.T0 + 600)
        cl.sync_tables()

        # land every DISCOVER on a chip that is NOT the owner: all 24 MAC
        # lookups route to `owner`, whose capacity is C < 24
        g = cl.geom.dhcp.sub._replace(axis=AXIS, n_shards=N)
        C = exchange_capacity(cl.b, g)
        assert C < len(same), (C, len(same))

        chip = (owner + 1) % N
        B = N * cl.b
        pkt = np.zeros((B, 512), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        for j, mac in enumerate(same):
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                              bytes([1, 3, 6, 51, 54])))
            f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(320, b"\x00"))
            row = chip * cl.b + j
            pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
            length[row] = len(f)

        out = cl.step(pkt, length, np.ones((B,), dtype=bool), self.T0, 0)
        lanes = slice(chip * cl.b, chip * cl.b + len(same))
        v = out["verdict"][lanes]
        n_tx = int((v == 2).sum())
        n_slow = int((v == 0).sum())
        assert n_tx == C, (n_tx, C)  # capacity lanes answered on device
        assert n_slow == len(same) - C  # overflow degrades to slow path
        assert int((v == 1).sum()) == 0  # and NOTHING is dropped


class TestRingShardSteering:
    """Cluster-level owner-routing invariant (VERDICT r3 item 3): the host
    ring steers a subscriber's traffic to the affinity shard, where its
    chip-local NAT/QoS state is consulted — and a frame arriving on a
    WRONG shard punts to the slow path instead of being silently
    translated/shaped (the all-state-is-owner-local safety property)."""

    T0 = 1_753_000_000

    def test_owner_shard_serves_wrong_shard_punts(self):
        n = 2
        cl = ShardedCluster(n, batch_per_shard=8)
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, ip_to_u32("10.0.0.1"),
                        lease_time=3600)
        sub_ip = ip_to_u32("10.0.0.77")
        owner, alloc = cl.allocate_nat(sub_ip, self.T0)
        assert alloc is not None
        o2, flow = cl.handle_new_flow(sub_ip, ip_to_u32("1.2.3.4"),
                                      40000, 443, 17, 600, self.T0)
        assert o2 == owner and flow is not None
        pub_ip, pub_port = flow
        qo = cl.set_qos(sub_ip, down_bps=1_000_000, up_bps=1_000_000)
        assert qo == owner
        assert cl.pub_ip_map()[pub_ip] == owner
        cl.sync_tables()

        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)
        assert ring.n_shards == n
        up = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, sub_ip,
                                ip_to_u32("1.2.3.4"), 40000, 443, b"u" * 100)
        down = packets.udp_packet(b"\x04" * 6, b"\x02" * 6,
                                  ip_to_u32("1.2.3.4"), pub_ip,
                                  443, pub_port, b"d" * 64)
        assert ring.shard_of(up, 1) == owner  # FLAG_FROM_ACCESS=1
        assert ring.rx_push(up, from_access=True)
        assert ring.rx_push(down, from_access=False)

        B, L = n * cl.b, 512
        pkt = np.zeros((B, L), dtype=np.uint8)
        ln = np.zeros((B,), dtype=np.uint32)
        fl = np.zeros((B,), dtype=np.uint32)
        assert ring.assemble_sharded(pkt, ln, fl) == 2
        base = owner * cl.b
        assert ln[base] == len(up) and ln[base + 1] == len(down)
        out = cl.step(pkt, ln, (fl & 1) != 0, self.T0 + 1, 1_000_000)
        assert int(out["verdict"][base]) == 3      # SNAT'd on the owner
        assert int(out["verdict"][base + 1]) == 3  # DNAT'd on the owner
        ring.complete(out["verdict"].astype(np.uint8),
                      np.asarray(out["out_pkt"]),
                      out["out_len"].astype(np.uint32), B)
        assert ring.stats()["fwd"] == 2

        # same upstream frame force-fed to the wrong shard: must PASS
        wrong = (owner + 1) % n
        wpkt = np.zeros((B, L), dtype=np.uint8)
        wln = np.zeros((B,), dtype=np.uint32)
        wrow = wrong * cl.b
        wpkt[wrow, : len(up)] = np.frombuffer(up, dtype=np.uint8)
        wln[wrow] = len(up)
        out2 = cl.step(wpkt, wln, np.ones((B,), dtype=bool),
                       self.T0 + 2, 2_000_000)
        assert int(out2["verdict"][wrow]) == 0  # punt, never mistranslate

    def test_affinity_matches_ring_for_ip_sweep(self):
        """Control-plane affinity and ring steering agree for any IP."""
        cl = ShardedCluster(N, batch_per_shard=8)
        ring = cl.make_ring(nframes=64, frame_size=2048, depth=32,
                            prefer_native=False)  # PyRing: same spec
        for i in range(64):
            ip = ip_to_u32(f"10.{i % 4}.{i // 4}.{i + 1}")
            up = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, ip,
                                    ip_to_u32("8.8.8.8"), 1000 + i, 443,
                                    b"x" * 32)
            assert cl.affinity_shard_ip(ip) == ring.shard_of(up, 1)


class TestMillionSubscriberShardedBuild:
    """Reference capacity on the sharded path (VERDICT r3 item 4): the
    reference sizes subscriber maps for 1,000,000 entries
    (/root/reference/bpf/maps.h:10). Build 1M hash-sharded over the
    8-way mesh with the vectorized owner split, run a real sharded step,
    and assert device hits — capacity is proven end-to-end, not claimed."""

    T0 = 1_753_000_000

    # compile-heavy scale smoke (~29s: 1M-row build + unique 8-way
    # trace); sharded step hits stay proven by TestShardedCluster —
    # slow tier runs the full 1M build
    @pytest.mark.slow
    def test_1m_subscribers_sharded_step_hits(self):
        n_subs = 1_000_000
        n = 8  # the full 8-way CPU mesh: ~125k subscribers per shard
        cl = ShardedCluster(n, batch_per_shard=64, sub_nbuckets=1 << 16,
                            vlan_nbuckets=64, cid_nbuckets=64, max_pools=32)
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
        for pid in range(16):  # /16 pools to hold 1M addresses
            cl.add_pool_all(pid + 1, ip_to_u32(f"10.{pid}.0.0") & 0xFFFF0000,
                            16, ip_to_u32("10.0.0.1"), lease_time=86400)
        macs = np.arange(n_subs, dtype=np.uint64) + 0x02AA00000000
        idx = np.arange(n_subs, dtype=np.uint64)
        owners = cl.add_subscribers_bulk(
            macs, pool_ids=(idx >> np.uint64(16)).astype(np.uint32) + 1,
            ips=((10 << 24) + 2 + idx).astype(np.uint32),
            lease_expiries=np.uint32(self.T0 + 86400))
        # every shard carries a real share of the 1M build
        per_shard = np.bincount(owners, minlength=n)
        assert per_shard.sum() == n_subs
        assert per_shard.min() > n_subs // n // 2, per_shard.tolist()
        cl.sync_tables()

        B = n * cl.b
        rng = np.random.default_rng(0x1A)
        pick = rng.integers(0, n_subs, size=B)
        pkt = np.zeros((B, 512), dtype=np.uint8)
        ln = np.zeros((B,), dtype=np.uint32)
        for row, i in enumerate(pick):
            mac = int(macs[i]).to_bytes(8, "big")[2:]
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER,
                                         xid=0x7000 + row)
            f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(320, b"\x00"))
            pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
            ln[row] = len(f)
        out = cl.step(pkt, ln, np.ones((B,), dtype=bool), self.T0 + 1, 0)
        n_tx = int((out["verdict"] == 2).sum())
        assert n_tx == B, f"{n_tx}/{B} DISCOVERs answered at 1M scale"
        from bng_tpu.ops.dhcp import ST_HIT

        assert int(out["dhcp_stats"][ST_HIT]) == B

    def test_shared_public_ip_across_shards_rejected(self):
        """Downstream steering is by-IP: shared public-IP ownership is not
        expressible, so the cluster must fail at CONSTRUCTION (review r4),
        never silently steer 3/4 of return traffic to a wrong shard."""
        with pytest.raises(ValueError, match="exclusively"):
            ShardedCluster(2, batch_per_shard=8,
                           public_ips=[ip_to_u32("203.0.113.9")])


class TestClusterRingLoop:
    """process_ring: the multichip production beat — steering ring ->
    sharded step -> verdict demux, end to end."""

    T0 = 1_753_000_000

    # compile-heavy (~34s unique trace); ring -> step -> verdict demux
    # stays proven in tier-1 by TestRingShardSteering and
    # test_sharded_serving's steered-ring loop — slow tier runs this one
    @pytest.mark.slow
    def test_ring_to_step_to_verdicts(self):
        n = 2
        cl = ShardedCluster(n, batch_per_shard=8)
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, ip_to_u32("10.0.0.1"),
                        lease_time=3600)
        mac = bytes.fromhex("02c0ffee0077")
        sub_ip = ip_to_u32("10.0.0.66")
        cl.add_subscriber(mac, pool_id=1, ip=sub_ip,
                          lease_expiry=self.T0 + 600)
        owner, _ = cl.allocate_nat(sub_ip, self.T0)
        cl.handle_new_flow(sub_ip, ip_to_u32("1.2.3.4"), 40000, 443, 17,
                           600, self.T0)
        cl.sync_tables()
        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)

        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x77)
        disc = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))
        up = packets.udp_packet(mac, b"\x04" * 6, sub_ip,
                                ip_to_u32("1.2.3.4"), 40000, 443, b"u" * 64)
        junk = packets.udp_packet(mac, b"\x04" * 6, ip_to_u32("10.0.0.99"),
                                  ip_to_u32("9.9.9.9"), 1, 2, b"j")
        for f in (disc, up, junk):
            assert ring.rx_push(f, from_access=True)
        got = cl.process_ring(ring, self.T0 + 1, 1_000_000)
        assert got == 3
        # demux: cached DISCOVER -> device OFFER on TX; SNAT'd flow ->
        # FWD; unknown-subscriber junk -> slow (PASS)
        assert ring.tx_pending() == 1
        assert ring.fwd_pending() == 1
        # the junk PASS lane was drained inline (no slow handler: frame
        # recycled — Engine._apply_ring_verdicts semantics)
        assert ring.slow_pending() == 0
        offer, _fl = ring.tx_pop()
        reply = dhcp_codec.decode(bytes(offer)[42:])
        assert reply.op == 2 and reply.xid == 0x77
        ring.fwd_pop()  # drain the SNAT'd frame
        # stats deltas folded (Engine.stats role)
        assert int(cl.stats["dhcp"].sum()) > 0
        assert int(cl.stats["nat"].sum()) > 0
        # empty ring: a beat is a no-op, no window leaks
        assert cl.process_ring(ring, self.T0 + 2, 2_000_000) == 0
        assert ring.free_frames() > 0

        # all-control batch rides the sharded DHCP fast lane; slow lanes
        # reach the host handler and its reply is injected on TX
        handled = []

        def slow(frame):
            handled.append(frame)
            return None

        p2 = dhcp_codec.build_request(bytes.fromhex("02c0ffee0088"),
                                      dhcp_codec.DISCOVER, xid=0x88)
        unknown = packets.udp_packet(bytes.fromhex("02c0ffee0088"),
                                     b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                     p2.encode().ljust(320, b"\x00"))
        assert ring.rx_push(disc, from_access=True)     # cached: device TX
        assert ring.rx_push(unknown, from_access=True)  # miss: slow handler
        assert cl.process_ring(ring, self.T0 + 3, 3_000_000,
                               slow_path=slow) == 2
        assert ring.tx_pending() == 1  # the cached OFFER
        assert len(handled) == 1 and handled[0] == unknown

        # a NAT new-flow punt creates the session on the OWNER shard:
        # the SAME flow forwards on the next beat
        flow2 = packets.udp_packet(mac, b"\x04" * 6, sub_ip,
                                   ip_to_u32("5.6.7.8"), 41000, 443,
                                   b"n" * 64)
        assert ring.rx_push(flow2, from_access=True)
        cl.process_ring(ring, self.T0 + 4, 4_000_000)  # punt handled inline
        assert ring.rx_push(flow2, from_access=True)
        cl.process_ring(ring, self.T0 + 5, 5_000_000)
        assert ring.fwd_pending() == 1  # packet 2 SNATs on device


@pytest.mark.slow  # shares TestClusterRingLoop's (n=2,b=8) trace — the
# whole geometry moves to the slow tier together or the ~30s compile
# just shifts here; steered ring->step->verdict stays in tier-1 via
# TestRingShardSteering + test_sharded_serving
class TestClusterRingPipelined:
    """Double-buffered multichip ring loop (VERDICT r4 weak #4): the
    sharded production beat overlaps host demux with mesh execution the
    same way Engine.process_ring_pipelined does for one chip."""

    T0 = 1_753_000_000

    def _cluster(self):
        cl = ShardedCluster(2, batch_per_shard=8)
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, ip_to_u32("10.0.0.1"),
                        lease_time=3600)
        mac = bytes.fromhex("02c0ffee0099")
        sub_ip = ip_to_u32("10.0.0.77")
        cl.add_subscriber(mac, pool_id=1, ip=sub_ip,
                          lease_expiry=self.T0 + 600)
        cl.sync_tables()
        return cl, mac, sub_ip

    def _discover(self, mac, xid):
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def test_two_window_overlap_and_flush(self):
        cl, mac, sub_ip = self._cluster()
        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)

        # call 1: dispatches batch A, retires nothing (pipe filling) —
        # the overlap evidence: A's verdicts are NOT on the ring yet
        assert ring.rx_push(self._discover(mac, 1), from_access=True)
        assert cl.process_ring_pipelined(ring, self.T0 + 1, 1_000_000) == 0
        assert ring.tx_pop() is None
        assert cl._inflight is not None

        # call 2: dispatches batch B, then retires A (device OFFER on TX)
        assert ring.rx_push(self._discover(mac, 2), from_access=True)
        assert cl.process_ring_pipelined(ring, self.T0 + 2, 2_000_000) == 1
        got = ring.tx_pop()
        assert got is not None
        reply = dhcp_codec.decode(bytes(got[0])[42:])
        assert reply.op == 2 and reply.xid == 1

        # flush retires the tail window; idempotent after
        assert cl.flush_pipeline() == 1
        got2 = ring.tx_pop()
        assert got2 is not None and dhcp_codec.decode(
            bytes(got2[0])[42:]).xid == 2
        assert cl.flush_pipeline() == 0
        # empty beats are no-ops and leak no window
        assert cl.process_ring_pipelined(ring, self.T0 + 3, 3_000_000) == 0
        assert cl._inflight is None
        # sync path still works after pipelined use (window accounting)
        assert ring.rx_push(self._discover(mac, 3), from_access=True)
        assert cl.process_ring(ring, self.T0 + 4, 4_000_000) == 1
        assert ring.tx_pending() == 1

    def test_pipelined_dispatch_failure_fails_closed(self):
        cl, mac, sub_ip = self._cluster()
        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)
        assert ring.rx_push(self._discover(mac, 1), from_access=True)
        assert cl.process_ring_pipelined(ring, self.T0 + 1, 1_000_000) == 0

        real_step, real_dhcp = cl._step, cl._dhcp_step

        def boom(*a, **k):
            raise RuntimeError("synthetic device error")

        cl._step = boom
        cl._dhcp_step = boom
        assert ring.rx_push(self._discover(mac, 2), from_access=True)
        with pytest.raises(RuntimeError, match="synthetic"):
            cl.process_ring_pipelined(ring, self.T0 + 2, 2_000_000)
        cl._step, cl._dhcp_step = real_step, real_dhcp

        # batch A's OFFER still arrived (FIFO retire before fail-close);
        # batch B dropped fail-closed; no window leaked
        got = ring.tx_pop()
        assert got is not None
        assert dhcp_codec.decode(bytes(got[0])[42:]).xid == 1
        assert cl._inflight is None
        assert ring.rx_push(self._discover(mac, 3), from_access=True)
        assert cl.process_ring(ring, self.T0 + 3, 3_000_000) == 1


class TestClusterPPPoE:
    """PPPoE on the multichip path (round 5): session DATA steers by the
    INNER src IP (bngring.h spec addition) to the shard holding the
    session row, where it decaps + SNATs in the sharded fused step;
    downstream DNATs + re-encaps on the public-IP owner shard."""

    T0 = 1_753_000_000
    AC = bytes.fromhex("02aabbccdd01")

    def _data_frame(self, mac, sid, src_ip, dst_ip, sport):
        from bng_tpu.control.pppoe import codec
        from bng_tpu.ops import pppoe as P

        inner = packets.udp_packet(mac, self.AC, src_ip, dst_ip,
                                   sport, 443, b"d" * 48)[14:]
        return codec.eth_frame(
            self.AC, mac, codec.ETH_PPPOE_SESSION,
            codec.PPPoEPacket(code=0, session_id=sid,
                              payload=codec.ppp_frame(P.PPP_IPV4,
                                                      inner)).encode())

    @pytest.mark.slow  # the pppoe_enabled sharded fused step is its
    # own ~20s compile used by this test alone; decap/SNAT device
    # semantics stay in tier-1 via test_pppoe_ops and the PPPoE
    # steering law via test_native_and_python_steering_agree_on_pppoe
    def test_steering_and_device_data_path(self):
        from bng_tpu.control.pppoe import codec

        n = 2
        cl = ShardedCluster(n, batch_per_shard=8, pppoe_enabled=True,
                            server_mac=self.AC, garden_enabled=False)
        cl.set_server_config_all(self.AC, ip_to_u32("10.0.0.1"))

        class Sess:
            session_id = 0x31
            client_mac = bytes.fromhex("02c0ffee0aa1")
            assigned_ip = ip_to_u32("10.0.0.111")

        owner = cl.pppoe_session_up(Sess())
        assert owner == cl.affinity_shard_ip(Sess.assigned_ip)
        nat_owner, _ = cl.allocate_nat(Sess.assigned_ip, self.T0)
        assert nat_owner == owner  # one affinity key places everything
        cl.handle_new_flow(Sess.assigned_ip, ip_to_u32("9.9.9.9"),
                           41000, 443, 17, 600, self.T0)
        cl.sync_tables()
        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)

        up = self._data_frame(Sess.client_mac, 0x31, Sess.assigned_ip,
                              ip_to_u32("9.9.9.9"), 41000)
        # the ring steers the PPPoE DATA frame by the INNER src ip
        assert ring.shard_of(up, 0x1) == owner
        # ...and PPPoE CONTROL by the sticky MAC hash (any shard ok)
        padi = codec.eth_frame(b"\xff" * 6, Sess.client_mac,
                               codec.ETH_PPPOE_DISCOVERY,
                               codec.PPPoEPacket(code=codec.CODE_PADI,
                                                 session_id=0,
                                                 payload=b"").encode())
        from bng_tpu.runtime.ring import shard_of as py_shard
        from bng_tpu.utils.net import fnv1a32
        assert ring.shard_of(padi, 0x1) == fnv1a32(Sess.client_mac) % n

        assert ring.rx_push(up, from_access=True)
        got = cl.process_ring(ring, self.T0 + 1, 1_000_000)
        assert got == 1
        assert ring.fwd_pending() == 1
        fwd, _fl = ring.fwd_pop()
        d = packets.decode(bytes(fwd))
        assert d.ethertype == 0x0800  # decapped on device
        nat_pub = cl.nat[owner].public_ips[0]
        assert d.src_ip == nat_pub  # SNAT'd on the OWNER shard
        assert int(cl.stats["pppoe"][0]) == 1  # PST_DECAP, psum-reduced

        # ---- downstream: to the public mapping, core side ----
        down = packets.udp_packet(bytes.fromhex("02deadbeef99"), self.AC,
                                  ip_to_u32("9.9.9.9"), nat_pub,
                                  443, d.src_port, b"r" * 24)
        assert ring.shard_of(down, 0x0) == owner  # public-IP ownership
        assert ring.rx_push(down, from_access=False)
        cl.process_ring(ring, self.T0 + 2, 2_000_000)
        assert ring.fwd_pending() == 1
        enc, _ = ring.fwd_pop()
        enc = bytes(enc)
        assert enc[0:6] == Sess.client_mac and enc[6:12] == self.AC
        assert int.from_bytes(enc[12:14], "big") == codec.ETH_PPPOE_SESSION
        pkt6 = codec.PPPoEPacket.decode(enc[14:])
        assert pkt6.session_id == 0x31

    def test_native_and_python_steering_agree_on_pppoe(self):
        """The C++ classifier and the PyRing mirror must stay bit-for-bit
        on the new PPPoE rule (spec: bngring.h)."""
        from bng_tpu.runtime.ring import NativeRing, load_native, shard_of

        if load_native() is None:
            pytest.skip("native lib unavailable")
        ring = NativeRing(nframes=64, frame_size=2048, depth=16, n_shards=4)
        try:
            rng = np.random.default_rng(5)
            for i in range(64):
                mac = bytes([0x02]) + bytes(rng.integers(0, 256, 5).tolist())
                sid = int(rng.integers(1, 0xFFFF))
                src = int(rng.integers(1, 2**32 - 1))
                dst = int(rng.integers(1, 2**32 - 1))
                f = self._data_frame(mac, sid, src, dst, 40000 + i)
                for fl in (0x1, 0x0):  # access and core side
                    assert ring.shard_of(f, fl) == shard_of(f, fl, 4, {})
        finally:
            ring.close()
