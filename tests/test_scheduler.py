"""Latency-tiered scheduler (runtime/scheduler.py + runtime/lanes.py).

Covers the ISSUE-1 acceptance surface on the CPU backend:
- deadline-close semantics: a partial express batch dispatches at
  max-wait, not before;
- express-never-behind-bulk: an express dispatch while a bulk step is in
  flight has no data dependency on it (the dhcp chain is never rebound
  by bulk), runs on its own device when one is available, and completes
  while the bulk step is still in flight;
- pipelining depth: never more than N bulk dispatches in flight;
- update-drain cadence: bulk host-table drains happen every
  `drain_every` dispatches only, express drains the fastpath every
  dispatch;
- bng_sched_* metric families exported;
- slow-path exceptions are logged (rate-limited), not swallowed.

Table geometry mirrors tests/test_e2e.py so the fused-pipeline compile
is shared across modules within one pytest process.
"""

from __future__ import annotations

import logging

import pytest

import jax

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.dhcp_server import DHCPServer
from bng_tpu.control.metrics import BNGMetrics
from bng_tpu.control.nat import NATManager
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.runtime.engine import AntispoofTables, Engine, QoSTables
from bng_tpu.runtime.lanes import (CLOSE_DEADLINE, CLOSE_FULL, CompletionRing,
                                   InflightEntry, Lane, LaneConfig)
from bng_tpu.runtime.scheduler import (LANE_BULK, LANE_EXPRESS,
                                       SchedulerConfig, TieredScheduler)
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.utils.net import ip_to_u32, parse_mac
from bng_tpu.utils.structlog import RateLimiter

SERVER_MAC = parse_mac("02:aa:bb:cc:dd:01")
SERVER_IP = ip_to_u32("10.0.0.1")


class FakeClock:
    def __init__(self, t=1_700_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def build_stack(batch_size=8, clock=None, slow_path="server"):
    clock = clock or FakeClock()
    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=24, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    qos = QoSTables(nbuckets=256)
    spoof = AntispoofTables(nbuckets=256)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                        fastpath_tables=fastpath, clock=clock)
    sp = server.handle_frame if slow_path == "server" else slow_path
    engine = Engine(fastpath, nat, qos, spoof, batch_size=batch_size,
                    slow_path=sp, clock=clock)
    return engine, server, clock


def discover(mac: bytes, xid: int) -> bytes:
    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
    p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def data_frame(i: int) -> bytes:
    mac = (0x02C0 << 32 | i).to_bytes(6, "big")
    return packets.udp_packet(mac, SERVER_MAC, ip_to_u32("10.0.0.9") + i,
                              ip_to_u32("93.184.216.34"), 40000 + i, 443,
                              b"x" * 64)


def mac_of(i: int) -> bytes:
    return (0x02B0 << 32 | i).to_bytes(6, "big")


# ---------------------------------------------------------------------------
# lanes: pure host-side policy (no device)
# ---------------------------------------------------------------------------

class TestLanePolicy:
    def test_full_close(self):
        lane = Lane(LaneConfig("x", batch=4, max_wait_us=1000, depth=2))
        now = 100.0
        for i in range(4):
            assert lane.push(b"f%d" % i, True, now, tag=i)
        assert lane.close_reason(now) == CLOSE_FULL
        pend, reason = lane.close_batch(now)
        assert reason == CLOSE_FULL and len(pend) == 4
        assert lane.stats.batches_full == 1

    def test_deadline_close_only_after_max_wait(self):
        lane = Lane(LaneConfig("x", batch=4, max_wait_us=200, depth=2))
        lane.push(b"f", True, 100.0)
        assert lane.close_reason(100.0 + 100e-6) is None  # 100us < 200us
        assert lane.close_reason(100.0 + 250e-6) == CLOSE_DEADLINE
        pend, reason = lane.close_batch(100.0 + 250e-6)
        assert reason == CLOSE_DEADLINE and len(pend) == 1
        assert lane.stats.batches_deadline == 1
        assert lane.stats.occupancy_avg() == pytest.approx(0.25)

    def test_overflow_drops(self):
        lane = Lane(LaneConfig("x", batch=2, max_wait_us=10, depth=1,
                               max_queue=3))
        assert all(lane.push(b"f", True, 1.0) for _ in range(3))
        assert not lane.push(b"f", True, 1.0)
        assert lane.stats.dropped_overflow == 1

    def test_completion_ring_overflow_is_fifo(self):
        ring = CompletionRing(depth=2)
        e = [InflightEntry(None, [], float(i), "full") for i in range(4)]
        assert ring.push(e[0]) is None
        assert ring.push(e[1]) is None
        assert ring.push(e[2]) is e[0]  # overflow hands back the OLDEST
        assert ring.push(e[3]) is e[1]
        assert len(ring) == 2


# ---------------------------------------------------------------------------
# scheduler over a live engine (CPU backend)
# ---------------------------------------------------------------------------

class TestClassification:
    def test_access_dhcp_express_else_bulk(self):
        engine, _, clock = build_stack()
        sched = TieredScheduler(engine, SchedulerConfig(), clock=clock)
        d = discover(mac_of(1), 0x11)
        assert sched.classify(d, from_access=True) == LANE_EXPRESS
        # core-side port-67 transit must NOT ride the express lane
        assert sched.classify(d, from_access=False) == LANE_BULK
        assert sched.classify(data_frame(1), from_access=True) == LANE_BULK


class TestOversizeFrames:
    def test_frame_over_pkt_slot_dropped_not_crash(self):
        """Rings admit frames up to frame_size (2048) but the engine slot
        is smaller; the scheduler must drop-and-count at submit, not blow
        up _pack_frames at dispatch (a wire frame must never kill the
        drive loop)."""
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(bulk_batch=8),
                                clock=clock)
        big = data_frame(0) + b"\x00" * engine.L  # > pkt_slot
        assert sched.submit(big) is None
        assert sched.oversize_dropped == 1
        assert len(sched.bulk) == 0
        sched.poll()  # nothing queued, nothing raises


class TestDeadlineClose:
    def test_partial_express_batch_ships_at_max_wait(self):
        engine, _, clock = build_stack()
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=64, express_max_wait_us=200.0), clock=clock)
        for i in range(3):
            assert sched.submit(discover(mac_of(i), 0x20 + i)) == LANE_EXPRESS
        sched.poll()
        assert sched.express.stats.batches == 0  # neither full nor aged
        clock.advance(100e-6)
        sched.poll()
        assert sched.express.stats.batches == 0  # 100us < max_wait
        clock.advance(150e-6)
        sched.poll()
        assert sched.express.stats.batches == 1  # deadline close fired
        assert sched.express.stats.batches_deadline == 1
        assert sched.express.stats.frames_dispatched == 3
        done = sched.drain_completions()
        assert len(done) == 3  # OFFERs from the slow path (fresh MACs)
        assert {c.lane for c in done} == {LANE_EXPRESS}
        replies = [c.frame for c in done if c.frame is not None]
        assert replies, "slow path should have produced OFFERs"


@pytest.mark.hotpath
@pytest.mark.slow  # compile-heavy pair (~38s: bulk_depth in-flight
# traces); still runs armed under make verify-sanitize ('hotpath or
# analysis or race' has no slow filter) and in verify-slow
class TestExpressNeverBehindBulk:
    def test_express_completes_while_bulk_in_flight(self):
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=64, bulk_batch=8, bulk_depth=2), clock=clock)

        # fill + dispatch exactly one bulk batch (manually, so nothing
        # retires it behind our back)
        for i in range(8):
            assert sched.submit(data_frame(i)) == LANE_BULK
        dhcp_before = jax.tree_util.tree_leaves(engine.tables.dhcp)
        now = clock()
        pend, reason = sched.bulk.close_batch(now)
        assert reason == CLOSE_FULL
        assert sched._dispatch_bulk(pend, now, reason) is None
        assert len(sched._bulk_ring) == 1  # bulk step in flight

        # the bulk dispatch must NOT have rebound the dhcp chain: that is
        # the data-dependency the replica design removes
        dhcp_after = jax.tree_util.tree_leaves(engine.tables.dhcp)
        assert all(a is b for a, b in zip(dhcp_before, dhcp_after))

        # express dispatch + retire with the bulk step still in flight
        for i in range(64):
            sched.submit(discover(mac_of(100 + i), 0x3000 + i))
        retired = sched._pump_express(clock())
        assert retired == 64
        done = sched.drain_completions()
        assert len(done) == 64
        assert {c.lane for c in done} == {LANE_EXPRESS}
        # ...and the bulk step is STILL in flight: express completion did
        # not wait for (or retire) it
        assert len(sched._bulk_ring) == 1

        # multi-device mesh: the express program ran on its own device,
        # so it did not even share an execution stream with bulk
        if len(jax.devices()) > 1:
            express_devs = {d for leaf in
                            jax.tree_util.tree_leaves(engine.tables.dhcp)
                            for d in leaf.devices()}
            bulk_entry = sched._bulk_ring._ring[0]
            bulk_devs = set(bulk_entry.res.verdict.devices())
            assert express_devs == {sched._express_dev}
            assert express_devs.isdisjoint(bulk_devs)

        # the flush barrier retires the bulk step
        sched.flush()
        bulk_done = sched.drain_completions()
        assert len(bulk_done) == 8
        assert {c.lane for c in bulk_done} == {LANE_BULK}

    def test_poll_services_express_before_bulk(self):
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=8, bulk_batch=8, bulk_depth=2), clock=clock)
        # both lanes have a full batch queued; one poll must dispatch
        # express first (completion order proves service order)
        for i in range(8):
            sched.submit(data_frame(i))
        for i in range(8):
            sched.submit(discover(mac_of(200 + i), 0x4000 + i))
        sched.poll()
        sched.flush()
        lanes_in_order = [c.lane for c in sched.drain_completions()]
        assert lanes_in_order.index(LANE_EXPRESS) < lanes_in_order.index(LANE_BULK)


@pytest.mark.hotpath
class TestPipelineDepth:
    def test_no_more_than_depth_in_flight(self):
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=8, bulk_depth=2, drain_every=1), clock=clock)
        max_seen = 0
        orig_push = sched._bulk_ring.push

        def spy_push(entry):
            nonlocal max_seen
            out = orig_push(entry)
            max_seen = max(max_seen, len(sched._bulk_ring))
            return out

        sched._bulk_ring.push = spy_push
        for i in range(5 * 8):  # five full bulk batches
            sched.submit(data_frame(i))
        retired = sched.poll()
        assert sched.bulk.stats.batches == 5
        # the ring may transiently hold depth+1 inside push(); what the
        # scheduler leaves in flight is bounded by depth
        assert max_seen <= 3
        assert len(sched._bulk_ring) <= 2
        retired += sched.flush()
        assert retired == 40


@pytest.mark.hotpath
class TestUpdateDrainCadence:
    def test_bulk_drains_every_n_dispatches(self):
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=8, bulk_depth=2, drain_every=3,
            overlap_drain=False), clock=clock)
        nat_calls = []
        orig = engine.nat.make_updates
        engine.nat.make_updates = lambda: (nat_calls.append(1), orig())[1]
        for i in range(6 * 8):  # six bulk dispatches under sustained load
            sched.submit(data_frame(i))
        sched.poll()
        sched.flush()
        assert sched.bulk.stats.batches == 6
        # drains at bulk_seq 0, 3 — every third dispatch only
        assert len(nat_calls) == 2
        assert sched._drains_applied == 2
        assert sched._drains_prefetched == 0
        # the no-drain steps reused the cached no-op scatter buffers
        assert engine.nat.sessions._empty_upd_cache

    def test_overlap_drain_prefetches_next_scatter(self):
        """overlap_drain (default): the drain-due step's scatter is built
        right after the PREVIOUS dispatch (overlapping step N's device
        execution), the in-dispatch cadence is unchanged, and a trailing
        prefetch that no batch consumed reaches the device at flush —
        never stranded (host dirty sets were already drained into it)."""
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=8, bulk_depth=2, drain_every=3), clock=clock)
        nat_calls = []
        orig = engine.nat.make_updates
        engine.nat.make_updates = lambda: (nat_calls.append(1), orig())[1]
        for i in range(6 * 8):
            sched.submit(data_frame(i))
        sched.poll()
        sched.flush()
        assert sched.bulk.stats.batches == 6
        # builds: in-dispatch at seq 0, prefetched for seq 3 and seq 6;
        # seq 6 never dispatched, so its batch applied at flush
        assert len(nat_calls) == 3
        assert sched._drains_prefetched == 2
        assert sched._drains_applied == 3  # seq 0, seq 3, flush-applied
        assert sched._prefetched_upd is None

    def test_overlap_drain_flush_ships_pending_delta(self):
        """A host write drained into a prefetched batch must be ON the
        device after flush even when no later bulk batch ever runs —
        the dangling-prefetch divergence hazard, pinned end-to-end."""
        import numpy as np

        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=8, bulk_depth=2, drain_every=1), clock=clock)
        for i in range(8):
            sched.submit(data_frame(i))
        sched.poll()
        sched.flush()  # drains consumed; a prefetched batch may linger
        # new host delta -> consumed by the NEXT prefetch, no more frames
        engine.qos.set_subscriber(ip_to_u32("10.9.9.9"), 8_000_000, 8_000_000)
        for i in range(8):
            sched.submit(data_frame(100 + i))
        sched.poll()
        sched.flush()
        assert engine.qos.up.dirty_count() == 0  # drained somewhere...
        slot = engine.qos.up._find(ip_to_u32("10.9.9.9"))
        assert slot is not None
        dev_row = np.asarray(engine.tables.qos_up.rows)[slot]
        assert np.array_equal(dev_row, engine.qos.up.rows[slot])  # ...and on device

    def test_no_drain_steps_carry_live_dense_config(self):
        """The no-op batch must NOT snapshot the dense config arrays: the
        step applies them wholesale, so a cached copy would revert live
        antispoof/garden/NAT config on every no-drain step."""
        engine, _, clock = build_stack()
        engine._empty_updates()  # primes the scatter caches
        engine.antispoof.add_allowed_range(ip_to_u32("172.16.0.0"), 12)
        after = engine._empty_updates()
        import numpy as np

        # upd layout: spoof ranges ride at index 5; a no-drain batch
        # built after the config change must carry it (no build-time
        # snapshot; jnp.asarray may or may not alias host memory, so
        # only the fresh-batch property is contractual)
        sp_ranges = np.asarray(after[5])
        assert (sp_ranges[:, 1] == ip_to_u32("172.16.0.0")).any()

    def test_express_drains_fastpath_every_dispatch(self):
        """The drain is LOGICALLY per-dispatch; PR 13 refined the build:
        a CLEAN mirror set serves the cached no-op batch (make_updates
        allocated fresh scatter buffers per call — ~40% of the express
        dispatch's host cost with zero dirty slots), while ANY dirty
        slot takes the real bounded drain on the very next dispatch
        (lease visibility pinned by the next test)."""
        engine, _, clock = build_stack()
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=8), clock=clock)
        fp_calls = []
        orig = engine.fastpath.make_updates
        engine.fastpath.make_updates = lambda: (fp_calls.append(1), orig())[1]
        for i in range(16):
            sched.submit(discover(mac_of(300 + i), 0x5000 + i))
        sched.poll()
        assert sched.express.stats.batches == 2
        # nothing was dirty at either dispatch: the cached no-op batch
        # served both — no fresh drain build on the clean fast path
        assert len(fp_calls) == 0
        # a host-side table write makes the NEXT dispatch drain for real
        engine.fastpath.add_subscriber(mac_of(390), pool_id=1,
                                       ip=ip_to_u32("10.0.0.90"),
                                       lease_expiry=int(clock()) + 600)
        for i in range(8):
            sched.submit(discover(mac_of(320 + i), 0x5100 + i))
        sched.poll()
        assert sched.express.stats.batches == 3
        assert len(fp_calls) == 1
        assert engine.fastpath.dirty_count() == 0  # delta shipped

    def test_pending_lease_reaches_device_via_express_drain(self):
        """A lease installed host-side between steps is visible to the
        very next express dispatch (the OFFER-correctness invariant the
        always-drain express rule protects)."""
        engine, _, clock = build_stack()
        sched = TieredScheduler(engine, SchedulerConfig(express_batch=8),
                                clock=clock)
        mac = mac_of(400)
        engine.fastpath.add_subscriber(mac, pool_id=1,
                                       ip=ip_to_u32("10.0.0.77"),
                                       lease_expiry=int(clock()) + 3600)
        out = sched.process([discover(mac, 0x6001)])
        assert len(out["tx"]) == 1  # on-device OFFER: the update landed


@pytest.mark.hotpath
class TestSchedulerDHCPCorrectness:
    def test_dora_then_fastpath_hit(self):
        engine, server, clock = build_stack()
        sched = TieredScheduler(engine, SchedulerConfig(express_batch=8),
                                clock=clock)
        mac = mac_of(500)
        out = sched.process([discover(mac, 0x7001)])
        assert len(out["slow"]) == 1
        offer = out["slow"][0][1]
        assert offer is not None
        od = packets.decode(offer)
        op = dhcp_codec.decode(od.payload)
        assert op.msg_type == dhcp_codec.OFFER
        req = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=0x7002,
                                       requested_ip=op.yiaddr,
                                       server_id=od.src_ip)
        req.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                            bytes([1, 3, 6, 51, 54])))
        rf = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                req.encode().ljust(300, b"\x00"))
        out2 = sched.process([rf])
        ack = out2["slow"][0][1]
        assert ack is not None
        assert dhcp_codec.decode(packets.decode(ack).payload).msg_type \
            == dhcp_codec.ACK
        # the lease is now in the device cache: next DISCOVER answers
        # on-device through the express lane (TX, no slow path)
        out3 = sched.process([discover(mac, 0x7003)])
        assert len(out3["tx"]) == 1 and not out3["slow"]

    def test_mixed_batch_fans_out_to_both_lanes(self):
        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=8, bulk_batch=8), clock=clock)
        frames = [discover(mac_of(600 + i), 0x8000 + i) for i in range(3)]
        frames += [data_frame(700 + i) for i in range(5)]
        out = sched.process(frames)
        done = {i for lst in (out["tx"], out["slow"], out["fwd"])
                for i, _ in lst} | set(out["dropped"])
        assert done == set(range(8))
        assert sched.express.stats.frames_dispatched == 3
        assert sched.bulk.stats.frames_dispatched == 5


class TestSchedulerMetrics:
    def test_bng_sched_families_exported(self):
        engine, _, clock = build_stack(batch_size=8)
        metrics = BNGMetrics()
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=8, bulk_batch=8), metrics=metrics, clock=clock)
        for i in range(8):
            sched.submit(discover(mac_of(800 + i), 0x9000 + i))
        for i in range(8):
            sched.submit(data_frame(900 + i))
        sched.poll()
        sched.flush()
        metrics.collect_scheduler(sched)
        text = metrics.expose()
        assert 'bng_sched_dispatches_total{lane="express",close="full"} 1' in text
        assert 'bng_sched_dispatches_total{lane="bulk",close="full"} 1' in text
        assert 'bng_sched_queue_depth{lane="express"} 0' in text
        assert 'bng_sched_frames_total{lane="bulk"} 8' in text
        assert "bng_sched_batch_occupancy_ratio_bucket" in text
        assert "bng_sched_dispatch_latency_seconds_bucket" in text


class TestSlowPathErrorsLogged:
    def _capture(self):
        records = []

        class H(logging.Handler):
            def emit(self, record):
                records.append(record)

        h = H()
        logging.getLogger("bng.slowpath").addHandler(h)
        return records, h

    def test_engine_process_logs_not_swallows(self):
        def boom(frame):
            raise ValueError("poisoned frame")

        engine, _, clock = build_stack(slow_path=boom)
        records, h = self._capture()
        try:
            out = engine.process([data_frame(0)])
            assert len(out["slow"]) == 1
            assert engine.stats.slow_errors == 1
            assert len(records) == 1
            assert records[0].bng_fields["error"].startswith("ValueError")
            assert records[0].exc_info is not None  # traceback preserved
        finally:
            logging.getLogger("bng.slowpath").removeHandler(h)

    def test_scheduler_lanes_log_and_rate_limit(self):
        def boom(frame):
            raise RuntimeError("handler down")

        engine, _, clock = build_stack(slow_path=boom)
        # deterministic limiter: 2-token bucket, no refill w/ fake clock
        engine._slow_err_log._limit = RateLimiter(rate=1.0, burst=2,
                                                  clock=clock)
        sched = TieredScheduler(engine, SchedulerConfig(express_batch=8),
                                clock=clock)
        records, h = self._capture()
        try:
            sched.process([discover(mac_of(950 + i), 0xA100 + i)
                           for i in range(8)])
            assert engine.stats.slow_errors == 8  # every failure counted
            assert len(records) == 2  # ...but the log is rate-limited
        finally:
            logging.getLogger("bng.slowpath").removeHandler(h)


class TestRateLimiter:
    def test_burst_then_refill(self):
        clock = FakeClock(0.0)
        rl = RateLimiter(rate=1.0, burst=2, clock=clock)
        assert rl.allow() == (True, 0)
        assert rl.allow() == (True, 0)
        ok, _ = rl.allow()
        assert not ok
        ok, _ = rl.allow()
        assert not ok
        clock.advance(1.0)  # one token refilled
        ok, suppressed = rl.allow()
        assert ok and suppressed == 2  # the two denied events reported


class TestLoadtestHarnessScheduler:
    def test_harness_routes_through_scheduler(self):
        from bng_tpu.loadtest import BenchmarkConfig, DHCPBenchmark

        engine, _, clock = build_stack(batch_size=8)
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=8, bulk_batch=8), clock=clock)
        cfg = BenchmarkConfig(batch_size=8, duration_s=0.05, warmup_s=0.02,
                              unique_macs=8, enable_renewals=False)
        import time as _t

        bench = DHCPBenchmark(sched, cfg, clock=_t.perf_counter)
        res = bench.run()
        assert res.program == "tiered_scheduler"
        assert res.requests > 0
        assert res.responses > 0
