"""AOT express OFFER path (ISSUE 13).

The acceptance surface of the minimal-program express lane:

- **Byte identity vs the full program**: the whole express path
  (admission descriptor -> AOT probe program -> host template patch-in)
  produces replies bit-identical to `_dhcp_jit`'s on-device compose,
  across >=4 table geometries and under BOTH table impls (`xla` and
  `pallas` in interpret mode), over the full addressing matrix
  (broadcast/unicast/relayed, VLAN/QinQ, option-82, DISCOVER/REQUEST,
  dns variants, expired/unknown -> slow).
- **Byte identity vs the codec**: an express template reply equals the
  slow-path server's codec-built reply for the same request (the
  express retire path routes through ReplyTemplate patch-in
  unconditionally).
- **AOT cache discipline**: a geometry hit serves without retracing
  (ops/express.TRACE_COUNT is a trace-time counter); a geometry miss
  falls back to the jit-full path LOUDLY (miss counter + flight-record
  trigger + ring-meta program identity), never silently.
- **SLO wiring**: the `device` stage budget (the paper's 50us) verdicts
  over express-fed breakdowns.

Geometries are kept tiny: the express program is small, but each
(geometry, impl) also compiles the full `_dhcp_jit` comparison program.
"""

from __future__ import annotations

import numpy as np
import pytest

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.dhcp_server import DHCPServer
from bng_tpu.control.metrics import BNGMetrics
from bng_tpu.control.nat import NATManager
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.ops import express as ex
from bng_tpu.ops import table as table_mod
from bng_tpu.runtime.engine import Engine
from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.telemetry import FlightRecorder, RecorderConfig
from bng_tpu.telemetry import slo
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.recorder import TRIG_EXPRESS_AOT_MISS
from bng_tpu.utils.net import ip_to_u32, parse_mac

pytestmark = pytest.mark.express

SERVER_MAC = parse_mac("02:aa:bb:cc:dd:01")
SERVER_IP = ip_to_u32("10.0.0.1")
NOW = 1_700_000_000


class FakeClock:
    def __init__(self, t=float(NOW)):
        self.t = t

    def __call__(self):
        return self.t


def mac_of(i: int) -> bytes:
    return (0x02B0 << 32 | i).to_bytes(6, "big")


def build_fp(sub_nb=256, vlan_nb=64, cid_nb=64) -> FastPathTables:
    """Three pools (dns1+dns2 / dns1 only / no dns) + the subscriber
    matrix the addressing cases below probe."""
    fp = FastPathTables(sub_nbuckets=sub_nb, vlan_nbuckets=vlan_nb,
                        cid_nbuckets=cid_nb, max_pools=8)
    fp.set_server_config(SERVER_MAC, SERVER_IP)
    fp.add_pool(1, ip_to_u32("10.0.0.0"), 24, SERVER_IP,
                ip_to_u32("8.8.8.8"), ip_to_u32("8.8.4.4"), 3600)
    fp.add_pool(2, ip_to_u32("10.1.0.0"), 16, ip_to_u32("10.1.0.1"),
                ip_to_u32("1.1.1.1"), 0, 7200)
    fp.add_pool(3, ip_to_u32("10.2.0.0"), 20, ip_to_u32("10.2.0.1"),
                0, 0, 600)
    fp.add_subscriber(mac_of(0), 1, ip_to_u32("10.0.0.50"), NOW + 600)
    fp.add_subscriber(mac_of(1), 2, ip_to_u32("10.1.0.60"), NOW + 600)
    fp.add_subscriber(mac_of(2), 3, ip_to_u32("10.2.0.70"), NOW + 600)
    fp.add_vlan_subscriber(100, 0, 1, ip_to_u32("10.0.0.80"), NOW + 600)
    fp.add_vlan_subscriber(200, 30, 2, ip_to_u32("10.1.0.90"), NOW + 600)
    fp.add_circuit_id_subscriber(b"port-7/0/1", 1, ip_to_u32("10.0.0.99"),
                                 NOW + 600)
    fp.add_subscriber(mac_of(9), 1, ip_to_u32("10.0.0.44"), NOW - 5)  # expired
    return fp


def dhcp_frame(mac, msg_type, vlans=None, giaddr=0, ciaddr=0,
               broadcast=False, circuit_id=b"", src_ip=0):
    pkt = dhcp_codec.build_request(mac, msg_type, giaddr=giaddr,
                                   ciaddr=ciaddr, broadcast=broadcast,
                                   circuit_id=circuit_id)
    if not circuit_id:
        pkt.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                            bytes([1, 3, 6, 15, 51, 54])))
    payload = pkt.encode().ljust(320, b"\x00")
    return packets.udp_packet(
        src_mac=mac, dst_mac=b"\xff" * 6, src_ip=src_ip,
        dst_ip=0xFFFFFFFF, src_port=68, dst_port=67, payload=payload,
        vlans=vlans)


def case_frames() -> list[bytes]:
    """The addressing/resolution matrix, one frame per case (8 total)."""
    return [
        dhcp_frame(mac_of(0), dhcp_codec.DISCOVER),                 # bcast OFFER
        dhcp_frame(mac_of(1), dhcp_codec.REQUEST),                  # ACK, dns1-only
        dhcp_frame(mac_of(2), dhcp_codec.DISCOVER, broadcast=True),  # no-dns pool
        dhcp_frame(mac_of(3), dhcp_codec.DISCOVER, vlans=[100]),    # vlan tier
        dhcp_frame(mac_of(4), dhcp_codec.DISCOVER, vlans=[200, 30]),  # qinq tier
        dhcp_frame(mac_of(5), dhcp_codec.DISCOVER,
                   circuit_id=b"port-7/0/1"),                       # opt82 tier
        dhcp_frame(mac_of(0), dhcp_codec.REQUEST,
                   giaddr=ip_to_u32("10.9.9.9")),                   # relayed
        dhcp_frame(mac_of(0), dhcp_codec.REQUEST,
                   ciaddr=ip_to_u32("10.0.0.50"),
                   src_ip=ip_to_u32("10.0.0.50")),                  # L2 unicast renew
    ]


def build_sched(fp: FastPathTables, express_batch: int,
                express_aot: bool, clock=None) -> TieredScheduler:
    clock = clock or FakeClock()
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=64, sub_nat_nbuckets=64)
    eng = Engine(fp, nat, batch_size=32, pkt_slot=512, clock=clock)
    return TieredScheduler(eng, SchedulerConfig(
        express_batch=express_batch, bulk_batch=32,
        express_aot=express_aot), clock=clock)


def run_express(sched: TieredScheduler, frames: list[bytes]) -> dict:
    out = sched.process(frames)
    return {"tx": dict(out["tx"]), "slow": sorted(i for i, _ in out["slow"])}


# ---------------------------------------------------------------------------
# descriptor extraction (host admission parse)
# ---------------------------------------------------------------------------

class TestDescriptor:
    def test_plain_discover(self):
        d = ex.parse_express(dhcp_frame(mac_of(0), dhcp_codec.DISCOVER))
        assert d is not None
        w = d.words
        assert w[ex.XD_FLAGS] & ex.XF_VALID
        assert w[ex.XD_FLAGS] & ex.XF_BCAST  # ciaddr==0 -> broadcast
        assert not (w[ex.XD_FLAGS] & (ex.XF_VLAN | ex.XF_CID | ex.XF_RELAYED))
        assert w[ex.XD_MAC_HI] == 0x02B0 and w[ex.XD_MAC_LO] == 0
        assert d.msg_type == dhcp_codec.DISCOVER and not d.relayed

    def test_vlan_and_qinq_key(self):
        d1 = ex.parse_express(dhcp_frame(mac_of(0), dhcp_codec.DISCOVER,
                                         vlans=[100]))
        assert d1.vlan_off == 4 and d1.words[ex.XD_VLAN] == (100 << 16)
        d2 = ex.parse_express(dhcp_frame(mac_of(0), dhcp_codec.DISCOVER,
                                         vlans=[200, 30]))
        assert d2.vlan_off == 8
        assert d2.words[ex.XD_VLAN] == (200 << 16) | 30
        assert d2.words[ex.XD_FLAGS] & ex.XF_VLAN

    def test_circuit_id_words(self):
        from bng_tpu.runtime.tables import pack_cid_host

        d = ex.parse_express(dhcp_frame(mac_of(0), dhcp_codec.DISCOVER,
                                        circuit_id=b"port-7/0/1"))
        assert d.words[ex.XD_FLAGS] & ex.XF_CID
        np.testing.assert_array_equal(
            d.words[ex.XD_CID0: ex.XD_CID0 + 8],
            pack_cid_host(b"port-7/0/1"))

    def test_relayed_flags(self):
        d = ex.parse_express(dhcp_frame(mac_of(0), dhcp_codec.REQUEST,
                                        giaddr=ip_to_u32("10.9.9.9")))
        assert d.relayed and not d.use_bcast
        assert d.words[ex.XD_FLAGS] & ex.XF_RELAYED

    def test_ineligible_frames_are_none(self):
        # non-DHCP, short, and wrong-message-type frames never probe
        assert ex.parse_express(b"\x00" * 60) is None
        data = packets.udp_packet(mac_of(0), b"\xff" * 6, 0, 0xFFFFFFFF,
                                  68, 53, b"x" * 300)
        assert ex.parse_express(data) is None
        rel = dhcp_frame(mac_of(0), dhcp_codec.RELEASE)
        assert ex.parse_express(rel) is None


# ---------------------------------------------------------------------------
# byte identity: express path vs the full _dhcp_jit program
# ---------------------------------------------------------------------------

GEOMETRIES = [
    dict(sub_nb=256, vlan_nb=64, cid_nb=64, batch=8),
    dict(sub_nb=128, vlan_nb=32, cid_nb=32, batch=8),
    dict(sub_nb=512, vlan_nb=128, cid_nb=64, batch=16),
    dict(sub_nb=256, vlan_nb=64, cid_nb=128, batch=8),
]

# each combo compiles the full _dhcp_jit comparison program (~10s on
# CPU, ~20s under pallas): geometry 0 stays in the fast tier under the
# default xla impl, the pallas column and the rest of the matrix ride
# the `slow` mark — `make verify-express` runs the WHOLE express marker
# (no slow deselect), so the 4-geometry x 2-impl identity claim stays
# machine-checked on every verify (pallas end-to-end coverage stays in
# tier-1 via test_pallas_table)
_IDENTITY_COMBOS = [
    pytest.param(gi, impl,
                 marks=(() if gi == 0 and impl == "xla"
                        else (pytest.mark.slow,)),
                 id=f"{gi}-{impl}")
    for gi in range(len(GEOMETRIES)) for impl in ("xla", "pallas")
]


class TestByteIdentity:
    @pytest.mark.parametrize("gi,impl", _IDENTITY_COMBOS)
    def test_express_matches_dhcp_jit(self, gi, impl, monkeypatch):
        monkeypatch.setattr(table_mod, "TABLE_IMPL", impl)
        g = GEOMETRIES[gi]
        frames = case_frames()
        sched_aot = build_sched(build_fp(g["sub_nb"], g["vlan_nb"],
                                         g["cid_nb"]),
                                g["batch"], express_aot=True)
        assert sched_aot.engine.table_impl == impl
        out_aot = run_express(sched_aot, frames)
        sched_jit = build_sched(build_fp(g["sub_nb"], g["vlan_nb"],
                                         g["cid_nb"]),
                                g["batch"], express_aot=False)
        out_jit = run_express(sched_jit, frames)

        # every on-device answer present on both paths, byte-identical
        assert set(out_aot["tx"]) == set(out_jit["tx"])
        assert len(out_aot["tx"]) == 8  # every case resolves on device
        for lane, frame in out_aot["tx"].items():
            assert frame == out_jit["tx"][lane], f"lane {lane} differs"
        assert out_aot["slow"] == out_jit["slow"]
        snap = sched_aot.stats_snapshot()["express"]
        assert snap["aot_dispatches"] >= 1 and snap["aot_misses"] == 0

    def test_expired_and_unknown_go_slow_on_both_paths(self, monkeypatch):
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "xla")
        frames = [dhcp_frame(mac_of(9), dhcp_codec.DISCOVER),  # expired
                  dhcp_frame(mac_of(77), dhcp_codec.DISCOVER)]  # unknown
        for aot in (True, False):
            out = run_express(build_sched(build_fp(), 8, aot), frames)
            assert out["tx"] == {} and out["slow"] == [0, 1]


# ---------------------------------------------------------------------------
# byte identity: express template reply vs the codec-built reply
# ---------------------------------------------------------------------------

class TestCodecIdentity:
    def test_express_reply_matches_codec_built(self):
        clock = FakeClock()
        fp = build_fp()
        pools = PoolManager(fp)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=24, gateway=SERVER_IP,
                            dns_primary=ip_to_u32("8.8.8.8"),
                            dns_secondary=ip_to_u32("8.8.4.4"),
                            lease_time=3600))
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            fastpath_tables=fp, clock=clock)
        mac = mac_of(40)
        frame = dhcp_frame(mac, dhcp_codec.DISCOVER)
        codec_reply = server.handle_frame(frame)
        assert codec_reply is not None
        yiaddr = dhcp_codec.decode(packets.decode(codec_reply).payload).yiaddr
        # install the same binding on the fast path; the express reply
        # must be byte-identical to the server's template-rendered frame
        fp.add_subscriber(mac, 1, yiaddr, NOW + 3600)
        sched = build_sched(fp, 8, express_aot=True, clock=clock)
        out = run_express(sched, [frame])
        assert out["tx"][0] == codec_reply


# ---------------------------------------------------------------------------
# AOT cache: hit without retrace, miss falls back loudly
# ---------------------------------------------------------------------------

class TestAotCache:
    def test_geometry_hit_serves_without_retrace(self):
        sched = build_sched(build_fp(), 8, express_aot=True)
        frames = case_frames()
        run_express(sched, frames)  # warm (compile happened at init)
        traces = ex.TRACE_COUNT
        for k in range(3):
            out = run_express(sched, frames)
            assert len(out["tx"]) == 8
        assert ex.TRACE_COUNT == traces, "AOT geometry hit retraced"
        # compiled for THIS lane's device (its own when >1 attached)
        assert sched.engine.express_aot(8, sched._express_dev) is not None
        snap = sched.stats_snapshot()["express"]
        assert snap["aot_dispatches"] >= 4 and snap["jit_dispatches"] == 0

    def test_geometry_miss_falls_back_loudly(self, tmp_path):
        recorder = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        with tele.armed(recorder=recorder):
            sched = build_sched(build_fp(), 8, express_aot=True)
            run_express(sched, case_frames())  # healthy AOT round
            assert recorder.meta.get("express_program") == "aot-express"
            # a live lane re-tune changes the batch geometry; no AOT
            # program exists for it — the dispatch must fall back to
            # the jit-full path and say so everywhere
            sched.express.cfg.batch = 16
            out = run_express(sched, case_frames())
            assert len(out["tx"]) == 8  # correctness preserved
            assert sched.express_aot_misses == 1
            assert sched.express_jit_dispatches == 1
            assert recorder.triggers.get(TRIG_EXPRESS_AOT_MISS, 0) == 1
            assert recorder.dump_paths, "miss must leave a flight dump"
            assert recorder.meta.get("express_program") == "jit-full"
        # the miss counter reaches the metrics surface
        m = BNGMetrics()
        m.collect_scheduler(sched)
        text = m.registry.expose()
        assert "bng_express_aot_miss_total 1" in text
        assert ('bng_express_program_dispatches_total{program="jit-full"} 1'
                in text)

    def test_compile_failure_degrades_to_jit_loudly(self, monkeypatch):
        """A permanent AOT compile failure must not brick the lane OR
        keep paying the per-frame admission parse: descriptors stop
        being extracted, every dispatch counts as a miss, and the
        jit-full path serves correct replies."""
        from bng_tpu.runtime.engine import Engine

        def boom(self, batch, device=None):
            raise RuntimeError("mosaic said no")

        monkeypatch.setattr(Engine, "compile_express_aot", boom)
        sched = build_sched(build_fp(sub_nb=64, vlan_nb=32, cid_nb=32),
                            8, express_aot=True)
        assert not sched._aot_ready
        out = run_express(sched, case_frames())
        assert len(out["tx"]) == 8  # jit-full serves
        assert all(p is None or p.desc is None
                   for p in sched.express.q)  # no admission parse
        assert sched.express_aot_misses >= 1
        assert sched.express_jit_dispatches >= 1

    def test_env_kill_switch_disables_aot(self, monkeypatch):
        monkeypatch.setenv("BNG_EXPRESS_AOT", "0")
        sched = build_sched(build_fp(), 8, express_aot=True)
        out = run_express(sched, case_frames())
        assert len(out["tx"]) == 8
        snap = sched.stats_snapshot()["express"]
        assert not snap["aot_enabled"]
        assert snap["jit_dispatches"] >= 1 and snap["aot_misses"] == 0

    def test_retire_renders_from_dispatch_epoch_config(self):
        """A pool-config rewrite between dispatch and retire must not
        leak into the reply: the retire renders from the pool/server
        snapshot taken at dispatch (the epoch the device verdict was
        computed against), never the live mirrors."""
        fp = build_fp()
        sched = build_sched(fp, 8, express_aot=True)
        now = float(NOW)
        frame = dhcp_frame(mac_of(0), dhcp_codec.DISCOVER)
        assert sched.submit(frame, now=now, tag=0) == "express"
        pend, reason = sched.express.close_batch(now, "flush")
        sched._dispatch_express(pend, now, reason)  # in flight (depth 2)
        old_gw = ip_to_u32("10.0.0.1")
        fp.add_pool(1, ip_to_u32("10.0.0.0"), 24, ip_to_u32("10.0.0.254"),
                    ip_to_u32("9.9.9.9"), 0, 1800)  # config moves on
        sched._retire_express_all()
        (c,) = sched.drain_completions()
        p = dhcp_codec.decode(packets.decode(c.frame).payload)
        assert p.opt(dhcp_codec.OPT_ROUTER) == old_gw.to_bytes(4, "big")
        assert p.opt(dhcp_codec.OPT_LEASE_TIME) == (3600).to_bytes(4, "big")

    def test_aot_dispatch_folds_device_stats(self):
        from bng_tpu.ops.dhcp import ST_HIT

        sched = build_sched(build_fp(), 8, express_aot=True)
        run_express(sched, case_frames())
        assert int(sched.engine.stats.dhcp[ST_HIT]) == 8
        assert sched.engine.stats.tx == 8


# ---------------------------------------------------------------------------
# SLO wiring smoke: the device budget verdicts over express breakdowns
# ---------------------------------------------------------------------------

class TestSloSmoke:
    def test_device_budget_verdicts_express_breakdown(self):
        assert slo.HEADLINE_TARGETS["offer_device_only_p99_us"] == 50.0
        with tele.armed() as tracer:
            sched = build_sched(build_fp(), 8, express_aot=True)
            run_express(sched, case_frames())
            # profiler-fenced device samples under budget -> ok
            tracer.observe_many(tele.DEVICE, [12.0] * 64)
            assert slo.evaluate(tracer.breakdown())["ok"]
            # an excursion over the 50us paper target must breach
            tracer.observe_many(tele.DEVICE, [400.0] * 640)
            verdict = slo.evaluate(tracer.breakdown())
            assert not verdict["ok"] and "device" in verdict["breaches"]


# ---------------------------------------------------------------------------
# ledger identity: the two architectures never trend against each other
# ---------------------------------------------------------------------------

class TestLedgerIdentity:
    def _line(self, path, v):
        return {"metric": "OFFER p99 device-isolated (scheduler)",
                "value": v, "unit": "us", "device": "TFRT_CPU_0",
                "express_path": path, "subscribers": 2000,
                "offer_device_only_p99_us": v,
                "env": {"platform": "cpu"}}

    def test_express_path_joins_cohort_key(self):
        from bng_tpu.telemetry import ledger

        a, b = self._line("jit-full", 40.0), self._line("aot-express", 40.0)
        assert ledger.cohort_key(a) != ledger.cohort_key(b)
        # unstamped legacy lines ARE the jit-full cohort
        legacy = self._line("jit-full", 40.0)
        del legacy["express_path"]
        assert ledger.cohort_key(legacy) == ledger.cohort_key(a)

    def test_cross_architecture_comparison_refused_naming_both(self):
        from bng_tpu.telemetry import ledger

        lines = [self._line("jit-full", 40.0 + i) for i in range(4)]
        lines.append(self._line("aot-express", 400.0))  # would "regress"
        rep = ledger.gate(lines)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        note = " ".join(rep.notes)
        assert "aot-express" in note and "jit-full" in note
