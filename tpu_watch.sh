#!/bin/bash
# Capture-on-return supervisor (VERDICT r3 item 1, ISSUE 18): probe the
# axon tunnel on a long backoff for the whole unattended window; the
# moment it answers, run the queued tpu_run.sh campaign (table A/B,
# autotune sweep, sharded headline, express-ab, host-ab, wire-ab,
# devloop k-sweep).  Exits after a completed window (/tmp/tpu_run.done)
# or when $TPU_WATCH_MAX_S elapses.
#
# Probes are `timeout`-bounded subprocesses: a dead tunnel costs one
# child per attempt and can never wedge the watcher (PERF_NOTES §3.5 —
# a stuck client can wedge the relay; always kill, never block).
#
# Artifacts are archived after EVERY campaign attempt and again on any
# watcher exit (trap), so a window that closes mid-campaign still
# leaves its partial ledger lines, bench JSON, flight-record dumps and
# transcripts in a timestamped directory — partial hardware numbers
# beat none, but only if they survive the tunnel.
set -u
cd "$(dirname "$0")"
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
RUN_LOG=${TPU_RUN_LOG:-/tmp/tpu_validation.log}
MAX_S=${TPU_WATCH_MAX_S:-39600}   # default: an 11 h round window
SLEEP_S=${TPU_WATCH_SLEEP_S:-150}
ARCHIVE_ROOT=${TPU_WATCH_ARCHIVE:-/tmp/tpu_artifacts}
DEST="$ARCHIVE_ROOT/$(date -u +%Y%m%dT%H%M%SZ)"
START=$(date +%s)

archive() {
  mkdir -p "$DEST"
  cp -f "$LOG" "$RUN_LOG" "$DEST/" 2>/dev/null
  cp -f bench_runs.jsonl "$DEST/" 2>/dev/null
  cp -f BENCH_*.json "$DEST/" 2>/dev/null
  FLIGHT_DIR=${BNG_TRACE_DIR:-${TMPDIR:-/tmp}/bng-flightrec}
  [ -d "$FLIGHT_DIR" ] && cp -rf "$FLIGHT_DIR" "$DEST/flightrec" 2>/dev/null
  [ -f /tmp/tpu_run.done ] && cp -f /tmp/tpu_run.done "$DEST/" 2>/dev/null
  echo "artifacts -> $DEST ($(date -u +%H:%M:%S))" | tee -a "$LOG"
}
trap archive EXIT

# a done-marker from a PREVIOUS round must not satisfy this watch
rm -f /tmp/tpu_run.done
echo "watch start $(date -u +%H:%M:%S) max=${MAX_S}s archive=$DEST" | tee -a "$LOG"
while true; do
  if [ -f /tmp/tpu_run.done ]; then
    echo "tpu_run.done present; watcher exiting $(date -u +%H:%M:%S)" | tee -a "$LOG"
    exit 0
  fi
  if [ $(( $(date +%s) - START )) -ge "$MAX_S" ]; then
    echo "watch window exhausted $(date -u +%H:%M:%S)" | tee -a "$LOG"
    exit 3
  fi
  if timeout 75 python -c "import jax, jax.numpy as j; (j.ones((8,8))@j.ones((8,8))).block_until_ready()" >/dev/null 2>&1; then
    echo "tunnel UP $(date -u +%H:%M:%S) — running tpu_run.sh" | tee -a "$LOG"
    bash tpu_run.sh >>"$LOG" 2>&1
    rc=$?
    echo "tpu_run.sh rc=$rc $(date -u +%H:%M:%S)" | tee -a "$LOG"
    # archive THIS attempt's artifacts now: rc!=0 means the tunnel died
    # mid-campaign, and the next window may never open
    archive
    # rc=0: full window captured.  Non-zero: keep watching; a later
    # window can still finish the remaining configs.
    [ $rc -eq 0 ] && exit 0
  fi
  sleep "$SLEEP_S"
done
