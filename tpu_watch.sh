#!/bin/bash
# Capture-on-return watcher (VERDICT r3 item 1): probe the axon tunnel on a
# long backoff for the whole unattended window; the moment it answers, run
# the full tpu_run.sh validation sequence.  Exits after a completed window
# (/tmp/tpu_run.done) or when $TPU_WATCH_MAX_S elapses.
#
# Probes are `timeout`-bounded subprocesses: a dead tunnel costs one child
# per attempt and can never wedge the watcher (PERF_NOTES §3.5 — a stuck
# client can wedge the relay; always kill, never block).
set -u
cd "$(dirname "$0")"
LOG=${TPU_WATCH_LOG:-/tmp/tpu_watch.log}
MAX_S=${TPU_WATCH_MAX_S:-39600}   # default: an 11 h round window
SLEEP_S=${TPU_WATCH_SLEEP_S:-150}
START=$(date +%s)
# a done-marker from a PREVIOUS round must not satisfy this watch
rm -f /tmp/tpu_run.done
echo "watch start $(date -u +%H:%M:%S) max=${MAX_S}s" | tee -a "$LOG"
while true; do
  if [ -f /tmp/tpu_run.done ]; then
    echo "tpu_run.done present; watcher exiting $(date -u +%H:%M:%S)" | tee -a "$LOG"
    exit 0
  fi
  if [ $(( $(date +%s) - START )) -ge "$MAX_S" ]; then
    echo "watch window exhausted $(date -u +%H:%M:%S)" | tee -a "$LOG"
    exit 3
  fi
  if timeout 75 python -c "import jax, jax.numpy as j; (j.ones((8,8))@j.ones((8,8))).block_until_ready()" >/dev/null 2>&1; then
    echo "tunnel UP $(date -u +%H:%M:%S) — running tpu_run.sh" | tee -a "$LOG"
    bash tpu_run.sh >>"$LOG" 2>&1
    rc=$?
    echo "tpu_run.sh rc=$rc $(date -u +%H:%M:%S)" | tee -a "$LOG"
    # rc=0: full window captured.  Non-zero: tunnel died mid-run — keep
    # watching; a later window can still finish the remaining configs.
    [ $rc -eq 0 ] && exit 0
  fi
  sleep "$SLEEP_S"
done
