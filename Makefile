# Test tiers (see ROADMAP.md "Tier-1 verify" and pytest.ini markers).
#
#   make verify       — the tier-1 gate: fast suite (-m 'not slow') under
#                       the hard timeout the CI driver enforces.
#   make verify-slow  — the compile-heavy tier (-m slow): the checkpoint
#                       round-trip, full DORA e2e, and every other test
#                       excluded from tier-1 to keep it under its timeout.
#   make verify-all   — both tiers.
#   make verify-load  — slow-path fleet loadtest smoke: 2 worker
#                       processes, a few thousand exchanges, CPU-only,
#                       < 60 s — fleet regressions fail fast outside the
#                       slow tier.
#   make verify-chaos — seeded chaos sweep: the chaos-marked tests
#                       (kill-at-every-fault-point, auditor self-tests,
#                       scenario suite) plus a double run of
#                       `bng chaos run --seed 7` compared byte-for-byte
#                       (the bit-determinism acceptance gate, covering
#                       the three zero-downtime transition scenarios AND
#                       the five FULL-SCALE storm scenarios — flash
#                       crowd at 100k subscribers; the engine-swap/CoA
#                       scenarios compile the fused pipeline once,
#                       ~30 s on CPU; ~90-120 s/run total). The long
#                       soak lives under @pytest.mark.slow.
#   make verify-perf  — SLO engine + perf-ledger tests (`perf` marker,
#                       tests/test_slo.py + tests/test_ledger.py, < 30 s)
#                       then `bng perf gate` against the repo's real
#                       bench_runs.jsonl (rc contract: 0 clean / 1
#                       regression / 2 internal / 3 incomparable-cohort).
#                       A prerequisite of `verify` (whose tier-1 line
#                       deselects `perf`; a bare ROADMAP tier-1 run
#                       still includes it).
#   make verify-storm — storm-suite tests (tests/test_storms.py, `storm`
#                       marker, < 60 s): fast deterministic variants of
#                       all five storms (same code as `bng chaos run`,
#                       reduced --storm-scale), the generator
#                       byte-identity proof, planted-violation tests for
#                       the v6/NAT-accounting/QoS-mirror audits, expiry
#                       batching + lease jitter, exhaustion hygiene.
#                       A prerequisite of `verify` (whose tier-1 line
#                       deselects `storm` so the suite runs once; a
#                       bare ROADMAP tier-1 run still includes it).
#   make verify-ops   — zero-downtime transition tests (< 60 s): live
#                       fleet resize / rolling restart / blue-green
#                       engine swap + rollback, the checkpoint N->M
#                       worker matrix, the `bng ctl` wire and the
#                       autoscaler (tests/test_ops.py, `ops` marker).
#   make verify-telemetry — telemetry tests with tracing ARMED via
#                       BNG_TELEMETRY=1 (< 30 s): disarmed-overhead
#                       bound, histogram merge laws, flight-recorder
#                       wrap + every anomaly trigger, Chrome-trace
#                       schema. The engine-compiling DORA e2e lives in
#                       the same file under @pytest.mark.slow (tier-1
#                       runs it; this target stays fast).
#   make verify-static — bngcheck static analyzer (< 30 s, no jax):
#                       `bng check` must exit 0 against the checked-in
#                       baseline (bng_tpu/analysis/baseline.json), then
#                       the analyzer's own planted-violation +
#                       clean-corpus tests run. Includes the
#                       concurrency-ownership pass (BNG060-BNG064):
#                       thread-entry discovery, call-graph context
#                       classification, lock-set propagation — warm
#                       runs reuse the mtime-keyed extraction cache
#                       (.bngcheck_cache.json). Part of `verify`: a PR
#                       that violates a dataplane invariant fails here
#                       before the test suite even starts.
#   make verify-kernels — Pallas table-probe kernel gate (ISSUE 11):
#                       the `kernels`-marked tests (interpret-mode
#                       bit-exactness vs xla_lookup AND the host
#                       mirror across every table geometry, impl
#                       dispatch, HLO no-narrow-gather pins, the
#                       sharded step under the kernel), the BNG014
#                       narrow-gather lint, and `bench.py --autotune
#                       --dry-run` (tiny CPU sweep to a temp ledger —
#                       proves the sweep/ledger plumbing without
#                       hardware). A prerequisite of `verify` (whose
#                       tier-1 line deselects `kernels`; a bare
#                       ROADMAP tier-1 run still includes them).
#                       Mosaic lowering itself is TPU-gated
#                       (runtime/verify.py, tpu_run.sh A/B step).
#   make verify-sharded — the ICI-sharded SERVING path (ISSUE 12):
#                       `sharded`-marked tests on the forced
#                       8-host-device CPU mesh (< 60 s): steered-ring
#                       missteer accounting (exact split from legit
#                       slow-path punts), sharded checkpoint N->M and
#                       N->1->N re-shard round-trips + reject paths,
#                       sharded blue/green swap + crash-at-flip, the
#                       composed `bng run --shards 2` DORA-and-renewal
#                       end-to-end, and the ledger n_shards cohort
#                       identity. A prerequisite of `verify` (whose
#                       tier-1 line deselects `sharded`; a bare ROADMAP
#                       tier-1 run still includes them).
#   make verify-express — AOT express OFFER-path gate (ISSUE 13):
#                       ALL `express`-marked tests (slow included —
#                       this target owns the full 4-geometry x 2-impl
#                       byte-identity matrix vs `_dhcp_jit`; the
#                       heavier combos are slow-marked so the ROADMAP
#                       tier-1 run carries only geometry 0 under both
#                       impls): descriptor-parse semantics, express-
#                       reply identity vs the codec-built reply, AOT
#                       cache hit-without-retrace and loud-miss
#                       fallback (counter + flight dump + ring-meta
#                       program identity), ledger express_path
#                       identity, and the SLO device-budget smoke. A
#                       prerequisite of `verify` (whose tier-1 line
#                       deselects `express`).
#   make verify-hostpath — vectorized host serving path (ISSUE 14):
#                       scalar-vs-vector byte identity over the frame
#                       corpus (classify/steer/peek kernels, PyRing
#                       assemble/complete/pops, batched admission,
#                       fleet pre-pass, staging pools, batched express
#                       render) in <60s. A prerequisite of `verify`
#                       (whose tier-1 line deselects `hostpath`; the
#                       ROADMAP tier-1 command still includes them).
#   make verify-wire  — AF_XDP wire pump (ISSUE 15): batch-pump
#                       bit-identity vs the scalar oracle over the
#                       edge-case corpus (partial fill, full fill
#                       ring, TX stall, headroom offsets, forged RX
#                       lengths), the frame-accounting satellite pins,
#                       and the memory-rung four-scenario serving twin
#                       (DORA + NAT punt + QoS drop + PPPoE through
#                       the full kernel-rings->pump->engine loop) in
#                       <60s, plus the `bench.py --wire-ab` plumbing
#                       smoke against a TEMP ledger (the repo ledger
#                       stays legacy-only). The veth e2e (slow tier)
#                       self-skips without CAP_NET_ADMIN. A
#                       prerequisite of `verify` (whose tier-1 line
#                       deselects `wire`; the ROADMAP tier-1 command
#                       still includes them).
#   make verify-sanitize — hotpath-marked engine/scheduler tests under
#                       BNG_SANITIZE=1 (transfer_guard + debug_nans):
#                       the dynamic cross-check of the static transfer
#                       lint. Best-effort on XLA:CPU (d2h guard inert
#                       there — analysis/sanitize.py documents the
#                       asymmetry); compile-bound, so not in tier-1.
#                       Also arms the @owned_by ownership assertions
#                       and re-runs the race-marked interleaving tests
#                       (tests/test_concurrency.py): the PR-7 race
#                       schedules forced with barriers, cross-context
#                       mutations raising OwnershipViolation.

SHELL := /bin/bash
PY ?= python
TIER1_TIMEOUT ?= 870
PYTEST_FLAGS = -q --continue-on-collection-errors -p no:cacheprovider \
               -p no:xdist -p no:randomly

.PHONY: verify verify-slow verify-all verify-load verify-chaos \
        verify-telemetry verify-static verify-sanitize verify-ops \
        verify-storm verify-perf verify-kernels verify-sharded \
        verify-express verify-hostpath verify-wire verify-cluster \
        verify-edge verify-devloop verify-fabric verify-multibox

verify: verify-static verify-storm verify-perf verify-kernels \
        verify-sharded verify-express verify-hostpath verify-wire \
        verify-cluster verify-edge verify-devloop verify-fabric \
        verify-multibox
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 $(TIER1_TIMEOUT) env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/ $(PYTEST_FLAGS) \
	-m 'not slow and not storm and not perf and not kernels and not sharded and not express and not hostpath and not wire and not cluster and not edge and not devloop and not fabric and not multibox' \
	2>&1 | tee /tmp/_t1.log

verify-sharded:
	set -o pipefail; \
	timeout -k 10 90 env JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m pytest tests/test_sharded_serving.py $(PYTEST_FLAGS) \
	  -m 'sharded and not slow' \
	&& echo "verify-sharded OK"

verify-express:
	set -o pipefail; \
	timeout -k 10 240 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_express.py $(PYTEST_FLAGS) \
	  -m 'express' \
	&& echo "verify-express OK"

verify-hostpath:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_hostpath.py $(PYTEST_FLAGS) \
	  -m 'hostpath and not slow' \
	&& echo "verify-hostpath OK"

verify-wire:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_wire_pump.py $(PYTEST_FLAGS) \
	  -m 'wire and not slow' \
	&& timeout -k 10 120 env JAX_PLATFORMS=cpu BNG_BENCH_PROBE_WINDOW=0 \
	  BNG_BENCH_TIMEOUT=90 BNG_BENCH_LOG=/tmp/_wire_ab.jsonl \
	  BNG_WIRE_AB_BATCH=1024 BNG_BENCH_LAT_STEPS=10 \
	  $(PY) bench.py --wire-ab \
	| $(PY) -c "import json,sys; \
	r=json.loads([l for l in sys.stdin if l.startswith('{')][-1]); \
	assert r['metric'].startswith('wire A/B'), r; \
	assert r['value'] >= 2.0, ('ISSUE 15 exit: vector pump < 2x', r); \
	assert r['pump_stats_match'], r; \
	print('verify-wire OK: vector %.1fx, ceiling %.2f -> %.2f Mpps' \
	% (r['value'], r['scalar_wire_mpps_ceiling'], \
	r['vector_wire_mpps_ceiling']))" \
	&& echo "verify-wire OK"

verify-cluster:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_cluster.py $(PYTEST_FLAGS) \
	  -m 'cluster and not slow' \
	&& echo "verify-cluster OK"

verify-edge:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_edge.py tests/test_qinq_ztp.py \
	  $(PYTEST_FLAGS) -m 'edge and not slow' \
	&& echo "verify-edge OK"

verify-devloop:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_devloop.py $(PYTEST_FLAGS) \
	  -m 'devloop' \
	&& echo "verify-devloop OK"

verify-fabric:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_fabric.py $(PYTEST_FLAGS) \
	  -m 'fabric and not slow' \
	&& echo "verify-fabric OK"

verify-multibox:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_multibox.py $(PYTEST_FLAGS) \
	  -m 'multibox and not slow' \
	&& echo "verify-multibox OK"

verify-kernels:
	set -o pipefail; \
	timeout -k 10 240 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/ $(PYTEST_FLAGS) \
	  -m 'kernels and not slow' \
	&& timeout -k 10 30 $(PY) -m bng_tpu.analysis --select gather \
	&& timeout -k 10 180 env JAX_PLATFORMS=cpu BNG_BENCH_PROBE_WINDOW=0 \
	  BNG_BENCH_TIMEOUT=150 $(PY) bench.py --autotune --dry-run \
	| $(PY) -c "import json,sys; \
	r=json.loads([l for l in sys.stdin if l.startswith('{')][-1]); \
	assert r['metric'] == 'autotune best point' and r['points'] >= 2, r; \
	assert r['best']['table_impl'] in ('xla', 'pallas'), r; \
	print('verify-kernels OK: best', r['best']['table_impl'], \
	'B=%d' % r['best']['batch'], '%.3f Mpps' % r['value'])" \
	&& echo "verify-kernels OK"

verify-slow:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ $(PYTEST_FLAGS) -m slow

verify-all: verify verify-slow

verify-chaos:
	set -o pipefail; \
	timeout -k 10 300 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_chaos.py $(PYTEST_FLAGS) -m 'chaos and not slow'
	set -o pipefail; \
	timeout -k 10 360 env JAX_PLATFORMS=cpu \
	$(PY) -m bng_tpu.cli chaos run --seed 7 > /tmp/_chaos_a.json \
	&& timeout -k 10 360 env JAX_PLATFORMS=cpu \
	$(PY) -m bng_tpu.cli chaos run --seed 7 > /tmp/_chaos_b.json \
	&& test -s /tmp/_chaos_a.json \
	&& cmp /tmp/_chaos_a.json /tmp/_chaos_b.json \
	&& echo "verify-chaos OK: report bit-deterministic (incl. the 4 \
	transition scenarios, 2 fabric scenarios + 5 full-scale storms)" \
	|| { echo "verify-chaos FAILED: scenario failure or same-seed \
	reports differ"; exit 1; }

verify-storm:
	set -o pipefail; \
	timeout -k 10 90 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_storms.py $(PYTEST_FLAGS) \
	  -m 'storm and not slow' \
	&& echo "verify-storm OK"

verify-perf:
	set -o pipefail; \
	timeout -k 10 30 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_slo.py tests/test_ledger.py \
	  $(PYTEST_FLAGS) -m 'perf and not slow' \
	&& timeout -k 10 30 env JAX_PLATFORMS=cpu \
	$(PY) -m bng_tpu.cli perf gate --ledger bench_runs.jsonl \
	&& echo "verify-perf OK"

verify-ops:
	set -o pipefail; \
	timeout -k 10 90 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_ops.py $(PYTEST_FLAGS) -m 'ops and not slow' \
	&& echo "verify-ops OK"

verify-telemetry:
	set -o pipefail; \
	timeout -k 10 30 env JAX_PLATFORMS=cpu BNG_TELEMETRY=1 \
	$(PY) -m pytest tests/test_telemetry.py $(PYTEST_FLAGS) \
	  -m 'telemetry and not slow' \
	&& echo "verify-telemetry OK"

verify-static:
	set -o pipefail; \
	timeout -k 10 30 $(PY) -m bng_tpu.analysis \
	&& timeout -k 10 60 env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_analysis.py $(PYTEST_FLAGS) \
	  -m 'analysis and not slow' \
	&& echo "verify-static OK"

verify-sanitize:
	set -o pipefail; \
	timeout -k 10 300 env JAX_PLATFORMS=cpu BNG_SANITIZE=1 \
	$(PY) -m pytest tests/test_sanitize.py tests/test_scheduler.py \
	  tests/test_dhcp_fastpath.py tests/test_concurrency.py $(PYTEST_FLAGS) \
	  -m 'hotpath or analysis or race' \
	&& echo "verify-sanitize OK"

verify-load:
	set -o pipefail; \
	timeout -k 10 60 env JAX_PLATFORMS=cpu $(PY) -m bng_tpu.cli loadtest \
	  --workers 2 --duration 2 --warmup 1 --macs 2000 --batch-size 256 \
	  --json \
	| $(PY) -c "import json,sys; r=json.load(sys.stdin); \
	assert r['responses'] >= 2000 and r['errors'] == 0, r; \
	assert r['fleet']['workers'] == 2, r['fleet']; \
	print('verify-load OK: %d req/s, %d responses, fleet admitted %d' \
	% (r['rps'], r['responses'], r['fleet']['admission']['admitted']))"
