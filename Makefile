# Test tiers (see ROADMAP.md "Tier-1 verify" and pytest.ini markers).
#
#   make verify       — the tier-1 gate: fast suite (-m 'not slow') under
#                       the hard timeout the CI driver enforces.
#   make verify-slow  — the compile-heavy tier (-m slow): the checkpoint
#                       round-trip, full DORA e2e, and every other test
#                       excluded from tier-1 to keep it under its timeout.
#   make verify-all   — both tiers.

SHELL := /bin/bash
PY ?= python
TIER1_TIMEOUT ?= 870
PYTEST_FLAGS = -q --continue-on-collection-errors -p no:cacheprovider \
               -p no:xdist -p no:randomly

.PHONY: verify verify-slow verify-all

verify:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 $(TIER1_TIMEOUT) env JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/ $(PYTEST_FLAGS) -m 'not slow' 2>&1 | tee /tmp/_t1.log

verify-slow:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ $(PYTEST_FLAGS) -m slow

verify-all: verify verify-slow
