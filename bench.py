"""Benchmark: sustained DHCP+NAT44 fast-path throughput on one chip.

Steady-state mix (the BASELINE.json headline): cached DHCP DISCOVER lanes
answered on device + established NAT44 flows SNAT'd on device, through the
full fused pipeline (parse -> antispoof -> DHCP -> NAT44 -> QoS) with the
tables at realistic scale.

Prints ONE JSON line:
  {"metric": "Mpps/chip DHCP+NAT44 fast path", "value": X, "unit": "Mpps",
   "vs_baseline": X / 12.5, ...}
vs_baseline: the north star is >=100 Mpps on a v5e-8 (BASELINE.md) =
12.5 Mpps/chip; >1.0 beats the target share for one chip.

Env knobs: BNG_BENCH_BATCH, BNG_BENCH_STEPS, BNG_BENCH_SUBS, BNG_BENCH_FLOWS.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _mark(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from bng_tpu.control import dhcp_codec, packets
    from bng_tpu.control.nat import NATManager
    from bng_tpu.ops.pipeline import PipelineGeom, PipelineTables, pipeline_step
    from bng_tpu.runtime.engine import AntispoofTables, QoSTables
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    _mark("jax imported; initializing device...")
    dev = jax.devices()[0]
    _mark(f"device: {dev}")
    on_tpu = dev.platform not in ("cpu",)
    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 512))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 200 if on_tpu else 10))
    N_SUBS = int(os.environ.get("BNG_BENCH_SUBS", 100_000 if on_tpu else 2_000))
    N_FLOWS = int(os.environ.get("BNG_BENCH_FLOWS", 100_000 if on_tpu else 2_000))
    L = 512
    now = 1_753_000_000

    t_setup = time.time()
    # ---- tables at scale ----
    sub_nb = 1 << max(10, (N_SUBS * 2 // 4).bit_length())  # ~50% load, 4-way
    fp = FastPathTables(sub_nbuckets=sub_nb, vlan_nbuckets=1 << 10,
                        cid_nbuckets=1 << 10, max_pools=64, stash=256)
    fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    # /16 pools to hold N_SUBS addresses
    n_pools = max(1, (N_SUBS >> 16) + 1)
    for pid in range(n_pools):
        fp.add_pool(pid + 1, ip_to_u32(f"10.{pid}.0.0") & 0xFFFF0000, 16,
                    ip_to_u32("10.0.0.1"), ip_to_u32("1.1.1.1"),
                    ip_to_u32("8.8.8.8"), 86400)

    macs = np.arange(N_SUBS, dtype=np.uint64) + 0x02AA00000000
    _mark(f"inserting {N_SUBS} subscribers...")
    for i in range(N_SUBS):
        ip = (10 << 24) | (i + 2)
        fp.add_subscriber(int(macs[i]), pool_id=(i >> 16) + 1, ip=ip,
                          lease_expiry=now + 86400)

    sess_nb = 1 << max(10, (N_FLOWS * 2 // 4).bit_length())
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1") + i for i in range(64)],
                     ports_per_subscriber=64,
                     sessions_nbuckets=sess_nb, sub_nat_nbuckets=sub_nb, stash=256)
    n_nat_subs = min(N_SUBS, max(1, N_FLOWS // 4))  # ~4 flows per subscriber
    _mark(f"creating {N_FLOWS} NAT flows...")
    flows = []
    for i in range(N_FLOWS):
        sub_i = i % n_nat_subs
        src_ip = (10 << 24) | (sub_i + 2)
        if sub_i == i:  # first flow of this subscriber
            nat.allocate_nat(src_ip, now)
        dst_ip = ip_to_u32("93.184.0.0") + (i // n_nat_subs)
        sport = 20000 + (i // n_nat_subs)
        got = nat.handle_new_flow(src_ip, dst_ip, sport, 443, 17, 100, now)
        if got is not None:
            flows.append((src_ip, dst_ip, sport))
    qos = QoSTables(nbuckets=1 << 10)
    spoof = AntispoofTables(nbuckets=1 << 10)

    _mark("uploading tables to device...")
    geom = PipelineGeom(dhcp=fp.geom, nat=nat.geom, qos=qos.geom, spoof=spoof.geom)
    tables = PipelineTables(
        dhcp=fp.device_tables(), nat=nat.device_tables(),
        qos_up=qos.up.device_state(), qos_down=qos.down.device_state(),
        spoof=spoof.bindings.device_state(),
        spoof_ranges=jnp.asarray(spoof.ranges),
        spoof_config=jnp.asarray(spoof.config),
    )

    # ---- steady-state batch: 20% cached DISCOVER, 80% established flows ----
    pkt = np.zeros((B, L), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    rng = np.random.default_rng(42)
    n_dhcp = B // 5
    for row in range(B):
        if row < n_dhcp:
            i = int(rng.integers(N_SUBS))
            mac = int(macs[i]).to_bytes(8, "big")[2:]
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER,
                                         xid=0x1000 + row)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
            f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(300, b"\x00"))
        else:
            src_ip, dst_ip, sport = flows[int(rng.integers(len(flows)))]
            f = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src_ip, dst_ip,
                                   sport, 443, b"x" * 180)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)

    pkt_d = jax.device_put(jnp.asarray(pkt))
    len_d = jax.device_put(jnp.asarray(length))
    fa_d = jax.device_put(jnp.ones((B,), dtype=bool))

    @jax.jit
    def step(tables, pkt, ln, fa, now_s, now_us):
        res = pipeline_step(tables, pkt, ln, fa, geom, now_s, now_us)
        return res.tables, res.verdict, res.dhcp_stats, res.nat_stats

    setup_s = time.time() - t_setup
    _mark(f"setup done in {setup_s:.1f}s; compiling fused pipeline (B={B})...")

    # ---- warmup / compile ----
    t_compile = time.time()
    tables, verdict, ds, ns = step(tables, pkt_d, len_d, fa_d,
                                   jnp.uint32(now), jnp.uint32(0))
    verdict.block_until_ready()
    compile_s = time.time() - t_compile
    _mark(f"compile+first step {compile_s:.1f}s; timing {STEPS} steps...")

    v = np.asarray(verdict)
    n_tx = int((v == 2).sum())
    n_fwd = int((v == 3).sum())
    hit_rate = (n_tx + n_fwd) / B

    # ---- timed sustained loop (per-step latency measured too) ----
    lat = []
    t0 = time.time()
    for k in range(STEPS):
        t1 = time.perf_counter()
        tables, verdict, ds, ns = step(tables, pkt_d, len_d, fa_d,
                                       jnp.uint32(now + 1 + k), jnp.uint32(k * 100))
        verdict.block_until_ready()
        lat.append(time.perf_counter() - t1)
    elapsed = time.time() - t0

    pps = STEPS * B / elapsed
    mpps = pps / 1e6
    lat_us = np.array(lat) * 1e6
    p50, p99 = float(np.percentile(lat_us, 50)), float(np.percentile(lat_us, 99))

    print(json.dumps({
        "metric": "Mpps/chip DHCP+NAT44 fast path",
        "value": round(mpps, 3),
        "unit": "Mpps",
        "vs_baseline": round(mpps / 12.5, 4),
        "batch": B,
        "steps": STEPS,
        "subscribers": N_SUBS,
        "flows": len(flows),
        "fastpath_hit_rate": round(hit_rate, 4),
        "batch_latency_p50_us": round(p50, 1),
        "batch_latency_p99_us": round(p99, 1),
        "device": str(dev),
        "compile_s": round(compile_s, 1),
        "setup_s": round(setup_s, 1),
    }))


if __name__ == "__main__":
    main()
