"""Benchmark: sustained DHCP+NAT44 fast-path throughput on one chip.

Steady-state mix (the BASELINE.json headline): cached DHCP DISCOVER lanes
answered on device + established NAT44 flows SNAT'd on device, through the
full fused pipeline (parse -> antispoof -> DHCP -> NAT44 -> QoS) with the
tables at realistic scale.

Prints ONE JSON line:
  {"metric": "Mpps/chip DHCP+NAT44 fast path", "value": X, "unit": "Mpps",
   "vs_baseline": X / 12.5, ...}
vs_baseline: the north star is >=100 Mpps on a v5e-8 (BASELINE.md) =
12.5 Mpps/chip; >1.0 beats the target share for one chip.

`--config N` runs one of the five BASELINE.json configs instead:
  1 DHCP slow path (control plane only, CPU)     [req/s]
  2 NAT44 conntrack, 100k concurrent flows       [Mpps]
  3 QoS token bucket, 10k subscribers            [Mpps]
  4 PPPoE + QinQ encap/decap batch               [Mpps]
  5 Full sharded pipeline over all devices       [Mpps]
  6 DHCP fast path standalone, 1M subscribers    [Mpps] (diagnostic)

Env knobs: BNG_BENCH_BATCH, BNG_BENCH_STEPS, BNG_BENCH_SUBS, BNG_BENCH_FLOWS.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np


def _mark(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)



def _build_dhcp_tables(N: int, now: int, stash: int = 256):
    """Subscriber fastpath tables at scale + the MAC array (shared by the
    headline and config 6 — one copy of the sizing/pool/bulk rules)."""
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    sub_nb = 1 << max(10, (N * 2 // 4).bit_length())  # ~50% load, 4-way
    fp = FastPathTables(sub_nbuckets=sub_nb, vlan_nbuckets=1 << 10,
                        cid_nbuckets=1 << 10, max_pools=64, stash=stash)
    fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    for pid in range(max(1, (N >> 16) + 1)):  # /16 pools to hold N addresses
        fp.add_pool(pid + 1, ip_to_u32(f"10.{pid}.0.0") & 0xFFFF0000, 16,
                    ip_to_u32("10.0.0.1"), ip_to_u32("1.1.1.1"),
                    ip_to_u32("8.8.8.8"), 86400)
    macs = np.arange(N, dtype=np.uint64) + 0x02AA00000000
    idx = np.arange(N, dtype=np.uint64)
    fp.add_subscribers_bulk(
        macs, pool_ids=(idx >> np.uint64(16)).astype(np.uint32) + 1,
        ips=((10 << 24) + 2 + idx).astype(np.uint32),
        lease_expiries=np.uint32(now + 86400))
    return fp, macs, sub_nb


def _discover_row(mac_u64: int | bytes, xid: int) -> bytes:
    from bng_tpu.control import dhcp_codec, packets

    mac = mac_u64 if isinstance(mac_u64, bytes) else int(mac_u64).to_bytes(8, "big")[2:]
    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
    p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _race_qos_impls(qos, ips, lens, steps: int, impls) -> dict:
    """Time qos_kernel under each aggregation impl (shared by config 3 and
    the headline's impl probe). Returns {impl: (mpps, p50, p99, cs)};
    failures land in _DIAG and never sink the other impl. PREFIX_IMPL is
    restored afterwards — callers decide whether to pin the winner."""
    import jax
    import jax.numpy as jnp

    import bng_tpu.ops.qos as qos_mod
    from bng_tpu.ops.qos import qos_kernel

    B = len(ips)
    active = jnp.ones((B,), dtype=bool)
    ips = jnp.asarray(ips)
    lens = jnp.asarray(lens)
    results: dict = {}
    old = qos_mod.PREFIX_IMPL
    for impl in impls:
        qos_mod.PREFIX_IMPL = impl
        try:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(t, i, l):
                r = qos_kernel(i, l, active, t, qos.geom, jnp.uint32(1))
                return r.table, r.allowed

            results[impl] = _timed_loop(
                step, (qos.up.device_state(), ips, lens), steps, B, carry=True)
            # re-key the loop diagnostics per impl (config 3's JSON line
            # carries one qos_<impl>_* pair per impl raced)
            for k in ("blocked_mpps", "pipelined_us_per_step"):
                if k in _DIAG:
                    _DIAG[f"qos_{impl}_{k}"] = _DIAG.pop(k)
            _mark(f"qos[{impl}]: {results[impl][0]:.3f} Mpps "
                  f"(p50 {results[impl][1]:.1f}us)")
        except Exception as e:  # one impl failing must not sink the other
            _mark(f"qos[{impl}] failed: {type(e).__name__}: {e}")
            _DIAG[f"qos_{impl}_error"] = f"{type(e).__name__}: {e}"
        finally:
            qos_mod.PREFIX_IMPL = old
    return results


def _race_table_impls(steps: int, impls, B: int = 8192,
                      nbuckets: int = 1 << 15, stash: int = 256) -> dict:
    """Time the impl-dispatched cuckoo probe under each table impl
    (fresh jit per impl via forced_impl, so the race never fights the
    engine's impl-keyed program caches). Returns {impl: (mpps, p50,
    p99, compile_s)}; one impl failing never sinks the other."""
    import jax
    import jax.numpy as jnp

    import bng_tpu.ops.table as table_mod
    from bng_tpu.ops.table import HostTable, device_lookup

    rng = np.random.default_rng(17)
    t = HostTable(nbuckets, 2, 8, stash=stash, name="probe_race")
    n = nbuckets * 2  # ~50% load, the sizing rule
    keys = np.unique(rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32),
                     axis=0)
    t.bulk_insert(keys, rng.integers(0, 2**32, size=(len(keys), 8),
                                     dtype=np.uint32))
    state = t.device_state()
    q = jnp.asarray(keys[rng.integers(0, len(keys), B)])
    results: dict = {}
    for impl in impls:
        try:
            @jax.jit
            def look(state, q, _impl=impl):
                with table_mod.forced_impl(_impl):
                    r = device_lookup(state, q, nbuckets, stash)
                return r.found, r.vals

            results[impl] = _timed_loop(look, (state, q), steps, B)
            for k in ("blocked_mpps", "pipelined_us_per_step"):
                if k in _DIAG:
                    _DIAG[f"table_{impl}_{k}"] = _DIAG.pop(k)
            _mark(f"table[{impl}]: {results[impl][0]:.3f} Mlookups/s "
                  f"(p50 {results[impl][1]:.1f}us)")
        except Exception as e:  # one impl failing must not sink the other
            _mark(f"table[{impl}] failed: {type(e).__name__}: {e}")
            _DIAG[f"table_{impl}_error"] = f"{type(e).__name__}: {e}"
    return results


def _pick_table_impl(on_tpu: bool) -> str:
    """Resolve the table-probe impl for this run (ISSUE 11).

    BNG_TABLE_IMPL=xla|pallas pins it. =auto self-times both impls on a
    standalone probe POST-COMPILE and pins the winner process-wide
    (table.set_auto_choice), so every program the run compiles after
    this — engine, sharded, bench steps — traces the winning kernel.
    The choice lands in _DIAG["table_impl"] on every emitted line."""
    import bng_tpu.ops.table as table_mod

    if table_mod.TABLE_IMPL != "auto" or not on_tpu:
        # off-TPU auto resolves to xla statically (Mosaic is TPU-only;
        # interpret-mode timing would be meaningless)
        return table_mod.current_impl_label()
    timing = _race_table_impls(30, ("xla", "pallas"))
    for k in [k for k in _DIAG if k.startswith("table_")]:
        _DIAG[f"probe_{k}"] = _DIAG.pop(k)
    if not timing:
        return table_mod.current_impl_label()
    best = max(timing, key=lambda k: timing[k][0])
    table_mod.set_auto_choice(best)
    _DIAG["table_impl_auto_raced"] = {
        impl: round(r[0], 3) for impl, r in timing.items()}
    return best


def _pick_qos_impl(on_tpu: bool) -> str:
    """Self-select the same-bucket-aggregation impl for the headline.

    BNG_QOS_PREFIX pins it; otherwise, on TPU, time both impls on a
    standalone qos_kernel (cheap compiles) and set ops.qos.PREFIX_IMPL to
    the winner — the unattended round-end run must not ship the slower
    kernel just because it is the default."""
    import bng_tpu.ops.qos as qos_mod
    from bng_tpu.runtime.engine import QoSTables

    if os.environ.get("BNG_QOS_PREFIX") or not on_tpu:
        return qos_mod.PREFIX_IMPL
    B = 8192
    qos = QoSTables(nbuckets=1 << 12)
    qos.bulk_set_subscribers(((10 << 24) + 2 + np.arange(4096)).astype(np.uint32),
                             down_bps=100_000_000, up_bps=20_000_000)
    rng = np.random.default_rng(3)
    ips = ((10 << 24) + 2 + rng.integers(0, 4096, size=B)).astype(np.uint32)
    lens = np.full((B,), 900, dtype=np.uint32)
    timing = _race_qos_impls(qos, ips, lens, 30, ("sort", "pallas"))
    # the probe ran at its own geometry (B=8192, 2^12 buckets, 30 steps) —
    # re-key its diagnostics so they cannot read as headline measurements
    for k in [k for k in _DIAG if k.startswith("qos_")]:
        _DIAG[f"probe_{k}"] = _DIAG.pop(k)
    if not timing:
        return qos_mod.PREFIX_IMPL  # both probes failed: keep the default
    best = max(timing, key=lambda k: timing[k][0])
    qos_mod.PREFIX_IMPL = best
    _DIAG["qos_impl"] = best
    return best


def main(on_tpu: bool) -> None:
    import jax
    import jax.numpy as jnp

    from bng_tpu.control import packets
    from bng_tpu.ops.pipeline import PipelineGeom, PipelineTables, pipeline_step
    from bng_tpu.runtime.engine import AntispoofTables, QoSTables

    _pick_qos_impl(on_tpu)

    dev = jax.devices()[0]
    _mark(f"device: {dev}")
    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 512))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 200 if on_tpu else 10))
    # reference scale: maps sized for 1M subscribers (bpf/maps.h:10)
    N_SUBS = int(os.environ.get("BNG_BENCH_SUBS", 1_000_000 if on_tpu else 2_000))
    N_FLOWS = int(os.environ.get("BNG_BENCH_FLOWS", 1_000_000 if on_tpu else 2_000))
    L = 512
    now = 1_753_000_000

    t_setup = time.time()
    _mark(f"bulk-inserting {N_SUBS} subscribers...")
    fp, macs, sub_nb = _build_dhcp_tables(N_SUBS, now)

    n_nat_subs = min(N_SUBS, max(1, N_FLOWS // 4))  # ~4 flows per subscriber
    _mark(f"bulk-creating {N_FLOWS} NAT flows for {n_nat_subs} subscribers...")
    nat, flows = _build_nat_flows(N_FLOWS, n_nat_subs, now,
                                  sub_nat_nbuckets=sub_nb)
    qos = QoSTables(nbuckets=1 << 10)
    spoof = AntispoofTables(nbuckets=1 << 10)

    _mark("uploading tables to device...")
    geom = PipelineGeom(dhcp=fp.geom, nat=nat.geom, qos=qos.geom, spoof=spoof.geom)
    tables = PipelineTables(
        dhcp=fp.device_tables(), nat=nat.device_tables(),
        qos_up=qos.up.device_state(), qos_down=qos.down.device_state(),
        spoof=spoof.bindings.device_state(),
        spoof_ranges=jnp.asarray(spoof.ranges),
        spoof_config=jnp.asarray(spoof.config),
    )

    # ---- steady-state batch: 20% cached DISCOVER, 80% established flows ----
    pkt = np.zeros((B, L), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    rng = np.random.default_rng(42)
    n_dhcp = B // 5
    for row in range(B):
        if row < n_dhcp:
            f = _discover_row(macs[int(rng.integers(N_SUBS))], 0x1000 + row)
        else:
            src_ip, dst_ip, sport = (int(x) for x in flows[int(rng.integers(len(flows)))])
            f = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src_ip, dst_ip,
                                   sport, 443, b"x" * 180)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)

    pkt_d = jax.device_put(jnp.asarray(pkt))
    len_d = jax.device_put(jnp.asarray(length))
    fa_d = jax.device_put(jnp.ones((B,), dtype=bool))

    # donate the tables: the engine's real step donates (engine.py), and an
    # un-donated bench re-copies every table buffer per step at 1M scale
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(tables, pkt, ln, fa, now_s, now_us):
        res = pipeline_step(tables, pkt, ln, fa, geom, now_s, now_us)
        return res.tables, res.verdict, res.dhcp_stats, res.nat_stats

    setup_s = time.time() - t_setup
    _mark(f"setup done in {setup_s:.1f}s; compiling fused pipeline (B={B})...")

    # ---- warmup / compile ----
    t_compile = time.time()
    tables, verdict, ds, ns = step(tables, pkt_d, len_d, fa_d,
                                   jnp.uint32(now), jnp.uint32(0))
    verdict.block_until_ready()
    compile_s = time.time() - t_compile
    _mark(f"compile+first step {compile_s:.1f}s; timing {STEPS} steps...")

    v = np.asarray(verdict)
    n_tx = int((v == 2).sum())
    n_fwd = int((v == 3).sum())
    hit_rate = (n_tx + n_fwd) / B

    # ---- timed sustained loop (per-step latency measured too) ----
    # telemetry spans decompose each step into dispatch (host enqueue)
    # vs device_wait (blocked sync) — the stage_breakdown quantities
    from bng_tpu.telemetry import spans as tele

    lat = []
    t0 = time.time()
    for k in range(STEPS):
        t1 = time.perf_counter()
        tok = tele.begin_batch(tele.LANE_BENCH, B)
        td = tele.t()
        tables, verdict, ds, ns = step(tables, pkt_d, len_d, fa_d,
                                       jnp.uint32(now + 1 + k), jnp.uint32(k * 100))
        tele.lap(tele.DISPATCH, td, tok)
        td = tele.t()
        verdict.block_until_ready()
        tele.lap(tele.DEVICE_WAIT, td, tok)
        tele.end_batch(tok)
        lat.append(time.perf_counter() - t1)
    elapsed = time.time() - t0

    pps = STEPS * B / elapsed
    mpps = pps / 1e6
    lat_us = np.array(lat) * 1e6
    p50, p99 = float(np.percentile(lat_us, 50)), float(np.percentile(lat_us, 99))

    # ---- op-level profile of the steady-state step ----
    # Default ON when a real accelerator is attached: the headline artifact
    # then carries its own diagnosis (top device ops), so a regression in
    # any kernel is attributable from BENCH_r{N}.json alone.
    profile_top = None
    want_profile = os.environ.get("BNG_BENCH_PROFILE", "1" if on_tpu else "0")
    if want_profile == "1":
        try:
            from bng_tpu.utils.profiling import format_report, profile_op_times

            _mark("profiling 10 steady-state steps...")

            # a NON-donating twin of the step: profiling is observational —
            # it must never consume the benchmark's live table buffers (a
            # mid-step failure would otherwise leave `tables` deleted)
            @jax.jit
            def step_prof(tables, pkt, ln, fa, now_s, now_us):
                res = pipeline_step(tables, pkt, ln, fa, geom, now_s, now_us)
                return res.verdict

            jax.block_until_ready(step_prof(tables, pkt_d, len_d, fa_d,
                                            jnp.uint32(now), jnp.uint32(0)))
            rep = profile_op_times(
                lambda: step_prof(tables, pkt_d, len_d, fa_d,
                                  jnp.uint32(now), jnp.uint32(0)),
                iters=10)
            _mark("\n" + format_report(rep))
            profile_top = [{"op": o.name, "us": round(o.us_per_iter, 1)}
                           for o in rep.ops[:8]]
        except Exception as e:  # profiling must never sink the benchmark
            _mark(f"profiling failed (continuing): {type(e).__name__}: {e}")
            _DIAG["profile_error"] = f"{type(e).__name__}: {e}"

    # ---- OFFER latency at small batch (true per-batch percentiles) ----
    # The p99-OFFER target (<50us @1M subs, BASELINE.json) is a tail metric:
    # measure the wall-time distribution of small all-DISCOVER batches — every
    # OFFER in a batch has latency <= that batch's wall time. The reference's
    # harness measures real percentiles (test/load/dhcp_benchmark.go:96-103).
    # Program parity: the reference's DHCP fast path is its OWN XDP program
    # (an XDP_TX reply never traverses the TC NAT/QoS hooks), so OFFER
    # latency is measured on the DHCP-only device program — the engine's
    # process_dhcp fast lane. The fused step's per-B latency is published
    # alongside in latency_curve.
    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch

    B_LAT = int(os.environ.get("BNG_BENCH_LAT_BATCH", 256 if on_tpu else 64))
    LAT_STEPS = int(os.environ.get("BNG_BENCH_LAT_STEPS", 400 if on_tpu else 20))
    _mark(f"latency mode: compiling B={B_LAT} all-DISCOVER batch (dhcp-only program)...")
    lpkt = np.zeros((B_LAT, L), dtype=np.uint8)
    llen = np.zeros((B_LAT,), dtype=np.uint32)
    for row in range(B_LAT):
        f = _discover_row(macs[int(rng.integers(N_SUBS))], 0x9000 + row)
        lpkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        llen[row] = len(f)
    lpkt_d = jax.device_put(jnp.asarray(lpkt))
    llen_d = jax.device_put(jnp.asarray(llen))
    lfa_d = jax.device_put(jnp.ones((B_LAT,), dtype=bool))

    @jax.jit
    def dhcp_step(dtables, pkt, ln, now_s):
        par = parse_batch(pkt, ln)
        res = dhcp_fastpath(pkt, ln, par, dtables, fp.geom, now_s)
        return res.is_reply, res.out_pkt, res.out_len

    dtables = tables.dhcp
    lreply, _, _ = dhcp_step(dtables, lpkt_d, llen_d, jnp.uint32(now))
    lreply.block_until_ready()
    llat = []
    for k in range(LAT_STEPS):
        t1 = time.perf_counter()
        tok = tele.begin_batch(tele.LANE_BENCH, B_LAT)
        td = tele.t()
        lreply, lout, lolen = dhcp_step(dtables, lpkt_d, llen_d,
                                        jnp.uint32(now + k))
        tele.lap(tele.DISPATCH, td, tok)
        td = tele.t()
        lreply.block_until_ready()
        tele.lap(tele.DEVICE_WAIT, td, tok)
        tele.end_batch(tok)
        llat.append(time.perf_counter() - t1)
    llat_us = np.array(llat) * 1e6
    offer_p50 = float(np.percentile(llat_us, 50))
    offer_p99 = float(np.percentile(llat_us, 99))
    offer_hits = int(np.asarray(lreply).sum())

    # ---- device-ONLY OFFER latency (profiler-fenced; VERDICT r5) ----
    # The <50us p99 target constrains DEVICE time. Blocked wall time
    # above includes host dispatch + sync artifacts (the axon tunnel's
    # ~63ms completion-poll bucket, PERF_NOTES §1); the XLA profiler's
    # per-execution events isolate the program itself, fenced by
    # jax.block_until_ready inside profile_step_durations. Published as
    # its own key so a tunnel artifact can never masquerade as device
    # cost again — and on XLA:CPU the closest isolate (per-execution
    # TfrtCpuExecutable time) is labeled "cpu-exec", never "device".
    offer_dev_p50 = offer_dev_p99 = 0.0
    device_source = "none"
    try:
        from bng_tpu.utils.profiling import profile_step_durations

        sd = profile_step_durations(
            lambda: dhcp_step(dtables, lpkt_d, llen_d, jnp.uint32(now)),
            iters=max(20, min(LAT_STEPS, 200)))
        if sd.us:
            offer_dev_p50 = sd.percentile(50)
            offer_dev_p99 = sd.percentile(99)
            device_source = sd.source
            tr = tele.tracer()
            if tr is not None:  # the `device` stage in stage_breakdown
                tr.observe_many(tele.DEVICE, sd.us)
        else:
            _DIAG["device_profile_error"] = "no per-execution events in trace"
    except Exception as e:  # profiling must never sink the benchmark
        _DIAG["device_profile_error"] = f"{type(e).__name__}: {e}"

    offer_profile_top = None
    if want_profile == "1":
        try:  # per-op profile of the DHCP-only program: a missed <50us
            # OFFER target must self-diagnose in the artifact
            from bng_tpu.utils.profiling import format_report, profile_op_times

            rep = profile_op_times(
                lambda: dhcp_step(dtables, lpkt_d, llen_d, jnp.uint32(now)),
                iters=10)
            _mark("\n[dhcp-only program]\n" + format_report(rep))
            offer_profile_top = [{"op": o.name, "us": round(o.us_per_iter, 1)}
                                 for o in rep.ops[:6]]
        except Exception as e:  # profiling must never sink the benchmark
            _mark(f"offer profiling failed (continuing): {type(e).__name__}: {e}")
            _DIAG["offer_profile_error"] = f"{type(e).__name__}: {e}" 

    # ---- batch-size/latency curve + dispatch decomposition (VERDICT r2
    # ask #3): per-B blocked percentiles (what a lone batch feels) AND the
    # depth-8 pipelined per-step time (device time with dispatch amortized
    # — on the axon tunnel a blocked sync can cost ~60ms for executables
    # over ~1ms device time, so publishing both separates real device cost
    # from host/tunnel sync overhead).
    curve = {}
    for Bs in (64, 256, 1024, 8192):
        if Bs > B:
            continue
        _mark(f"latency curve: B={Bs}...")
        cur = {k: jax.device_put(v) for k, v in
               (("pkt", jnp.asarray(lpkt[:Bs] if Bs <= B_LAT else
                                    np.resize(lpkt, (Bs, L)))),
                ("ln", jnp.asarray(np.resize(llen, (Bs,)))),
                ("fa", jnp.ones((Bs,), dtype=bool)))}
        tables, v0, _, _ = step(tables, cur["pkt"], cur["ln"], cur["fa"],
                                jnp.uint32(now), jnp.uint32(0))
        v0.block_until_ready()
        blocked = []
        for k in range(60):
            t1 = time.perf_counter()
            tables, v0, _, _ = step(tables, cur["pkt"], cur["ln"], cur["fa"],
                                    jnp.uint32(now + k), jnp.uint32(k))
            v0.block_until_ready()
            blocked.append(time.perf_counter() - t1)
        depth = 8
        t1 = time.perf_counter()
        vs = []
        for k in range(depth * 8):
            tables, v0, _, _ = step(tables, cur["pkt"], cur["ln"], cur["fa"],
                                    jnp.uint32(now + k), jnp.uint32(k))
            vs.append(v0)
            if len(vs) > depth:  # keep `depth` steps in flight
                vs.pop(0).block_until_ready()
        jax.block_until_ready(vs)
        pipelined = (time.perf_counter() - t1) / (depth * 8)
        bl = np.asarray(blocked) * 1e6
        curve[str(Bs)] = {
            "blocked_p50_us": round(float(np.percentile(bl, 50)), 1),
            "blocked_p99_us": round(float(np.percentile(bl, 99)), 1),
            "pipelined_us_per_step": round(pipelined * 1e6, 1),
        }

    extra = dict(_DIAG)
    line = {
        "metric": "Mpps/chip DHCP+NAT44 fast path",
        "value": round(mpps, 3),
        "unit": "Mpps",
        "vs_baseline": round(mpps / 12.5, 4),
        "batch": B,
        "steps": STEPS,
        "subscribers": N_SUBS,
        "flows": int(len(flows)),
        "fastpath_hit_rate": round(hit_rate, 4),
        "batch_latency_p50_us": round(p50, 1),
        "batch_latency_p99_us": round(p99, 1),
        "offer_p50_us": round(offer_p50, 1),
        "offer_p99_us": round(offer_p99, 1),
        # the quantity the 50us target actually constrains (fenced
        # device/executable time, never host wall) — see device_time_source
        "offer_device_only_p50_us": round(offer_dev_p50, 1),
        "offer_device_only_p99_us": round(offer_dev_p99, 1),
        "device_time_source": device_source,
        "offer_latency_batch": B_LAT,
        "offer_program": "dhcp_fastpath",  # reference parity: own XDP prog
        "offer_hits": offer_hits,
        "latency_curve": curve,
        # per-stage p50/p99 from the telemetry tracer (dispatch /
        # device_wait are host decomposition; `device` is the fenced
        # profiler distribution above)
        "stage_breakdown": (tele.tracer().breakdown()
                            if tele.tracer() is not None else {}),
        **({"profile_top_ops": profile_top} if profile_top else {}),
        **({"offer_profile_top_ops": offer_profile_top} if offer_profile_top else {}),
        "device": str(dev),
        "compile_s": round(compile_s, 1),
        "setup_s": round(setup_s, 1),
        **extra,
    }
    _finalize_diag()
    line = _order_line({**line, **{k: v for k, v in _DIAG.items()
                                   if k not in line}})
    print(json.dumps(line))
    _persist(line)


def _timed_loop(step, args, steps, batch, carry: bool = False):
    """Compile, warm, time; returns (mpps, p50_us, p99_us, compile_s).

    Two timing modes per PERF_NOTES §3 (the axon tunnel adds a ~63ms
    completion-poll penalty to every *blocked* call whose device time
    exceeds ~0.2-1ms, so blocked-each timing is artifact-dominated):
      - blocked-each -> true end-to-end batch latency (p50/p99)
      - async-pipelined (enqueue all, block once) -> device throughput;
        this is the Mpps reported, matching the engine's double-buffered
        dispatch model. The blocked-loop rate lands in _DIAG.

    carry=True: output[0] is threaded back as args[0] each step — the
    donated-table discipline the engine uses (a step that donates its
    state must rebind it, or the next call reads a consumed buffer)."""
    import jax

    t_c = time.time()
    out = step(*args)
    jax.block_until_ready(out)
    compile_s = time.time() - t_c
    if carry:
        args = (out[0],) + tuple(args[1:])
    lat = []
    t0 = time.time()
    for _ in range(steps):
        t1 = time.perf_counter()
        out = step(*args)
        jax.block_until_ready(out)
        if carry:
            args = (out[0],) + tuple(args[1:])
        lat.append(time.perf_counter() - t1)
    dt = time.time() - t0
    lat_us = np.asarray(lat) * 1e6
    blocked_mpps = steps * batch / dt / 1e6

    # async-pipelined: enqueue the whole window, block once at the end
    t0 = time.time()
    for _ in range(steps):
        out = step(*args)
        if carry:
            args = (out[0],) + tuple(args[1:])
    jax.block_until_ready(out)
    dt_p = time.time() - t0
    pipelined_mpps = steps * batch / dt_p / 1e6

    _DIAG["blocked_mpps"] = round(blocked_mpps, 3)
    _DIAG["pipelined_us_per_step"] = round(dt_p / steps * 1e6, 1)
    return (pipelined_mpps, float(np.percentile(lat_us, 50)),
            float(np.percentile(lat_us, 99)), compile_s)


# merged into every emitted JSON line: backend-fallback diagnostics etc.
_DIAG: dict = {}

# keys that must lead the emitted JSON object (VERDICT "What's weak" §1:
# a CPU-fallback run was read as a TPU headline because the flag sat
# buried mid-object — a reader skimming the first line must hit it first)
_LEAD_KEYS = ("backend_fallback", "backend_error", "flight_record",
              "tunnel_precheck")


def _order_line(line: dict) -> dict:
    """Reorder so backend-fallback diagnostics lead the object (dicts
    are insertion-ordered; json.dumps preserves it)."""
    lead = {k: line[k] for k in _LEAD_KEYS if k in line}
    if not lead:
        return line
    return {**lead, **{k: v for k, v in line.items() if k not in lead}}


def _finalize_diag() -> None:
    """Pre-print hook: a CPU-fallback run must dump the flight recorder
    (telemetry armed by _child_dispatch) and carry the dump path in its
    JSON — the gray-failure class where three rounds published CPU
    numbers while every metric looked healthy."""
    if "backend_fallback" in _DIAG and "flight_record" not in _DIAG:
        from bng_tpu.telemetry import spans as tele

        path = tele.trigger("backend_fallback",
                            _DIAG.get("backend_error", ""))
        if path:
            _DIAG["flight_record"] = path


def _probe_window() -> float:
    """Capture-on-return probe window (s), shared by child and supervisor.

    Round 3's deliverable fell back to CPU after one 150 s probe while the
    tunnel happened to be down (VERDICT r3 weak #4).  The unattended
    round-end run now keeps probing for BNG_BENCH_PROBE_WINDOW seconds
    (default 900) before accepting the CPU fallback; the supervisor's child
    timeout is extended by the same amount so a long probe can never eat
    the run budget.  Set to 0 for the old single-shot behavior (tests,
    interactive runs on a known-up chip)."""
    return max(0.0, float(os.environ.get("BNG_BENCH_PROBE_WINDOW", 900)))


def _persist(line: dict) -> None:
    """Append every bench result to bench_runs.jsonl (r2 ADVICE: per-config
    measurements must live in artifacts, not review prose). The appender
    stamps the ledger schema (schema_version, run_id, ts —
    telemetry/ledger.py) so every new line is perf-gate-comparable."""
    from bng_tpu.telemetry import ledger

    try:
        ledger.append(ledger.default_ledger_path(), line)
    except OSError:
        pass  # read-only checkout: stdout still carries the result


def _emit(metric, value, unit, baseline, **extra):
    _finalize_diag()
    line = _order_line({"metric": metric, "value": round(value, 3),
                        "unit": unit,
                        "vs_baseline": round(value / baseline, 4),
                        **extra, **_DIAG})
    print(json.dumps(line))
    _persist(line)


def config1_dhcp_slowpath():
    """BASELINE config 1: DHCP slow path through the worker FLEET.

    Reference target: 50k req/s combined — the reference gets there with
    concurrent Go; the slow-path fleet (control/fleet.py) is the
    architecture this gate assumes, so the headline number drives the
    fleet (BNG_BENCH_WORKERS processes, default 4; 1 = legacy
    single-thread path). The single-worker run is always measured too
    and published alongside (single_rps / fleet_speedup).

    Env knobs: BNG_BENCH_WORKERS, BNG_BENCH_FLEET_BATCH, BNG_BENCH_SECS.
    """
    from bng_tpu.control import dhcp_codec, packets
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.utils.net import ip_to_u32

    smac = bytes.fromhex("02aabbccdd01")
    sip = ip_to_u32("10.0.1.1")

    def mkpools(prefix_len=16):
        pools = PoolManager(None)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=prefix_len, gateway=sip,
                            dns_primary=ip_to_u32("1.1.1.1"),
                            lease_time=3600))
        return pools

    macs = [(0x02B1 << 32 | i).to_bytes(6, "big") for i in range(1000)]

    def discover(mac, xid):
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    # pre-build the client frames: the measured quantity is the SERVER
    # (the reference's load harness generates client traffic outside the
    # server process entirely)
    frames = [discover(m, 1000 + i) for i, m in enumerate(macs)]
    secs = float(os.environ.get("BNG_BENCH_SECS", 5))

    # -- single-thread baseline (the pre-fleet architecture) --
    server = DHCPServer(smac, sip, mkpools())
    n = 0
    lat = []
    t0 = time.perf_counter()
    deadline = t0 + secs
    while time.perf_counter() < deadline:
        f = frames[n % len(frames)]
        t1 = time.perf_counter()
        reply = server.handle_frame(f)
        lat.append(time.perf_counter() - t1)
        assert reply is not None
        n += 1
    dt = time.perf_counter() - t0
    single_rps = n / dt
    lat_us = np.asarray(lat) * 1e6
    extra = {
        "p50_us": round(float(np.percentile(lat_us, 50)), 1),
        "p99_us": round(float(np.percentile(lat_us, 99)), 1),
        "requests": n,
        # busy_rps = server capacity from time actually spent in
        # handle_frame (wall-clock rps on a shared host is
        # scheduler-noise-bound; both are published)
        "server_busy_rps": round(n / float(np.sum(lat)), 1),
        "single_rps": round(single_rps, 1),
    }

    # default: drive the fleet only where it can win (>= 4 real cores).
    # Below that the parent's serial section leaves no headroom, and on
    # syscall-virtualized kernels (gVisor-style sandboxes) the pipe
    # ping-pong collapses outright (PERF_NOTES §6) — the published
    # headline must not regress just because the host is small.
    # BNG_BENCH_WORKERS overrides either way.
    ncpu = os.cpu_count() or 1
    workers = int(os.environ.get("BNG_BENCH_WORKERS",
                                 "4" if ncpu >= 4 else "1"))
    if workers <= 1:
        _emit("DHCP slow-path req/s (config 1)", single_rps, "req/s",
              50_000.0, workers=1, **extra)
        return

    # -- the fleet (big per-worker messages: the pipe write overlaps the
    # children's compute — PERF_NOTES §6) --
    B = int(os.environ.get("BNG_BENCH_FLEET_BATCH", 2048))
    pools = mkpools()
    from bng_tpu.control.admission import AdmissionConfig

    fleet = SlowPathFleet(
        FleetSpec.from_pool_manager(smac, sip, pools, slice_size=4096,
                                    low_watermark=512),
        n_workers=workers, pools=pools, mode="process",
        # inbox >= the bench batch: shedding is a correctness feature,
        # not something a throughput bench should silently trip
        admission=AdmissionConfig(inbox_capacity=max(512, B)))
    _mark(f"fleet up: {workers} workers")
    try:
        n = 0
        i = 0
        blat = []
        t0 = time.perf_counter()
        deadline = t0 + secs
        while time.perf_counter() < deadline:
            batch = [(k, frames[(i + k) % len(frames)]) for k in range(B)]
            t1 = time.perf_counter()
            out = fleet.handle_batch(batch)
            blat.append(time.perf_counter() - t1)
            n += sum(1 for _lane, r in out if r is not None)
            i += B
        dt = time.perf_counter() - t0
        snap = fleet.stats_snapshot()
    finally:
        fleet.close()
    fleet_rps = n / dt
    per_req_us = np.asarray(blat) * 1e6 / B
    _emit("DHCP slow-path req/s (config 1)", fleet_rps, "req/s", 50_000.0,
          workers=workers, fleet_batch=B,
          fleet_speedup=round(fleet_rps / single_rps, 2),
          fleet_p50_us=round(float(np.percentile(per_req_us, 50)), 1),
          fleet_p99_us=round(float(np.percentile(per_req_us, 99)), 1),
          fleet_shed=sum(snap["admission"]["shed"].values()),
          fleet_refills=snap["refills"], **extra)


def _build_nat_flows(n_flows, n_subs, now, sub_nat_nbuckets=None):
    """Shared NAT+flows construction for the headline mix and config 2.

    Sizes the public-IP pool to actually hold n_subs port blocks
    ((65535-1024+1)//64 = 1008 64-port blocks per public IP), bulk-allocates
    blocks, and bulk-creates ~4 flows/subscriber. Returns (nat, flows[K,3])
    and records any allocation shortfall in _DIAG.
    """
    from bng_tpu.control.nat import NATManager
    from bng_tpu.utils.net import ip_to_u32

    sess_nb = 1 << max(10, (n_flows * 2 // 4).bit_length())
    n_pub = max(4, -(-n_subs // 1008) + 1)
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1") + i for i in range(n_pub)],
                     ports_per_subscriber=64, sessions_nbuckets=sess_nb,
                     sub_nat_nbuckets=sub_nat_nbuckets or sess_nb, stash=256)
    fi = np.arange(n_flows, dtype=np.int64)
    src_ips = ((10 << 24) + 2 + fi % n_subs).astype(np.uint32)
    dst_ips = (ip_to_u32("93.184.0.0") + fi // n_subs).astype(np.uint32)
    # BNG_BENCH_EIM_SHARE=k: k flows share one internal endpoint
    # (src_ip, src_port) — the reference's 4M-session/2M-EIM geometry
    # (bpf/nat44.c:38-40) is share=2; default 1 = every flow its own
    # endpoint (distinct dst per shared sport keeps 5-tuples unique)
    share = max(1, int(os.environ.get("BNG_BENCH_EIM_SHARE", "1")))
    sports = (20000 + (fi // n_subs) // share).astype(np.uint32)
    made = nat.bulk_allocate_nat(np.unique(src_ips), now)
    _, _, ok = nat.bulk_flows(src_ips, dst_ips, sports,
                              np.uint32(443), np.uint32(17), 100, now)
    flows = np.stack([src_ips, dst_ips, sports], axis=1)[ok]
    if made < n_subs or len(flows) < n_flows:
        _DIAG["nat_blocks_allocated"] = made
        _DIAG["nat_flow_shortfall"] = int(n_flows - len(flows))
    return nat, flows


def _nat_fixture(n_flows, B, L=512):
    from bng_tpu.control import packets

    now = 1_753_000_000
    nat, flows = _build_nat_flows(n_flows, max(1, n_flows // 4), now)
    rng = np.random.default_rng(7)
    pkt = np.zeros((B, L), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    for row in range(B):
        src, dst, sport = (int(x) for x in flows[int(rng.integers(len(flows)))])
        f = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src, dst, sport, 443,
                               b"x" * 180)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)
    return nat, pkt, length, now


def config2_nat44(on_tpu):
    """BASELINE config 2: NAT44 conntrack at 100k concurrent flows."""
    import jax
    import jax.numpy as jnp

    from bng_tpu.ops.nat44 import nat44_kernel, nat44_update_sessions
    from bng_tpu.ops.parse import parse_batch

    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 256))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 5))
    N = int(os.environ.get("BNG_BENCH_FLOWS", 100_000 if on_tpu else 2_000))
    t_b = time.time()
    nat, pkt, length, now = _nat_fixture(N, B)
    build_s = time.time() - t_b
    t_u = time.time()
    tables = nat.device_tables()
    hbm_gb = sum(x.nbytes for x in jax.tree.leaves(tables)) / 1e9
    pkt_d = jax.device_put(jnp.asarray(pkt))
    len_d = jax.device_put(jnp.asarray(length))
    upload_s = time.time() - t_u

    # VERDICT r2 weak #4: the headline NAT number must include the
    # accounting pass (counter/TCP-state scatters), and the session table
    # must thread through donated — that's what the engine's step costs.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(tables, pkt, ln):
        par = parse_batch(pkt, ln)
        res = nat44_kernel(pkt, ln, par, tables, nat.geom, jnp.uint32(now))
        sessions = nat44_update_sessions(tables.sessions, res, par, ln,
                                         keep=res.translated,
                                         now_s=jnp.uint32(now))
        return tables._replace(sessions=sessions), res.out_pkt, res.translated, res.stats

    mpps, p50, p99, cs = _timed_loop(step, (tables, pkt_d, len_d), STEPS, B,
                                     carry=True)
    _emit("NAT44 Mpps @100k flows (config 2)", mpps, "Mpps", 12.5,
          batch=B, flows=N, p50_us=round(p50, 1), p99_us=round(p99, 1),
          compile_s=round(cs, 1), includes_accounting=True,
          build_s=round(build_s, 1), upload_s=round(upload_s, 1),
          nat_tables_gb=round(hbm_gb, 2),
          eim_endpoints=len(nat.eim))


def config3_qos(on_tpu):
    """BASELINE config 3: per-subscriber token bucket, 10k subscribers.

    Times BOTH same-bucket-aggregation impls (sort path and the Pallas MXU
    equality-matmul) unless BNG_QOS_PREFIX pins one, emits the winner as
    the headline value and the loser in the diagnostics — so a round-end
    unattended run picks the right kernel and records the evidence."""
    from bng_tpu.runtime.engine import QoSTables

    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 256))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 5))
    N = int(os.environ.get("BNG_BENCH_SUBS", 10_000 if on_tpu else 1_000))
    qos = QoSTables(nbuckets=1 << max(10, (N * 2 // 4).bit_length()))
    qos.bulk_set_subscribers(((10 << 24) + 2 + np.arange(N)).astype(np.uint32),
                             down_bps=100_000_000, up_bps=20_000_000)
    rng = np.random.default_rng(9)
    ips = ((10 << 24) + 2 + rng.integers(0, N, size=B)).astype(np.uint32)
    lens = np.full((B,), 900, dtype=np.uint32)

    pinned = os.environ.get("BNG_QOS_PREFIX")
    impls = [pinned] if pinned else (["sort", "pallas"] if on_tpu else ["sort"])
    results = _race_qos_impls(qos, ips, lens, STEPS, impls)
    if not results:
        raise RuntimeError("both QoS impls failed")
    best = max(results, key=lambda k: results[k][0])
    for impl, (mpps, p50, p99, cs) in results.items():
        if impl != best:
            _DIAG[f"qos_{impl}_mpps"] = round(mpps, 3)
            _DIAG[f"qos_{impl}_p50_us"] = round(p50, 1)
    mpps, p50, p99, cs = results[best]
    _emit("QoS token-bucket Mpps @10k subs (config 3)", mpps, "Mpps", 12.5,
          batch=B, subscribers=N, impl=best, p50_us=round(p50, 1),
          p99_us=round(p99, 1), compile_s=round(cs, 1))


def config4_pppoe(on_tpu):
    """BASELINE config 4: PPPoE + QinQ encap/decap batched on device."""
    import jax
    import jax.numpy as jnp

    from bng_tpu.control import packets
    from bng_tpu.control.pppoe import codec
    from bng_tpu.ops import pppoe as P
    from bng_tpu.ops.parse import parse_batch
    from bng_tpu.ops.table import HostTable, TableGeom
    from bng_tpu.utils.net import ip_to_u32

    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 256))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 5))
    N = int(os.environ.get("BNG_BENCH_SUBS", 10_000 if on_tpu else 1_000))
    from bng_tpu.runtime.tables import PPPoEFastPathTables

    ac = bytes.fromhex("02aabbccdd01")
    nb = 1 << max(10, (N * 2 // 4).bit_length())
    # the SAME host-table stack Engine(pppoe=...) runs — the bench must
    # measure the production geometry, not a hand-built lookalike
    pp = PPPoEFastPathTables(nbuckets=nb, stash=128, server_mac=ac)
    by_sid, geom = pp.by_sid, pp.geom

    class _Sess:
        pass

    for i in range(N):
        s = _Sess()
        s.session_id = i + 1
        s.client_mac = (0x02B2 << 32 | i).to_bytes(6, "big")
        s.assigned_ip = (10 << 24) | (i + 2)
        pp.session_up(s)
    rng = np.random.default_rng(11)
    pkt = np.zeros((B, 512), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    for rowi in range(B):
        i = int(rng.integers(N))
        mac = (0x02B2 << 32 | i).to_bytes(6, "big")
        ip_pkt = packets.udp_packet(mac, ac, (10 << 24) | (i + 2),
                                    ip_to_u32("8.8.8.8"), 5000, 53,
                                    b"d" * 160)[14:]
        ppp = codec.ppp_frame(P.PPP_IPV4, ip_pkt)
        pppoe = codec.PPPoEPacket(code=0, session_id=i + 1, payload=ppp).encode()
        f = codec.eth_frame(ac, mac, codec.ETH_PPPOE_SESSION, pppoe,
                            vlans=[100, (i % 4000) + 1])
        pkt[rowi, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[rowi] = len(f)
    tab = by_sid.device_state()

    @jax.jit
    def step(tab, pkt, ln):
        par = parse_batch(pkt, ln)
        res = P.pppoe_decap(pkt, ln, par.vlan_offset, par.ethertype, tab, geom)
        return res.out_pkt, res.done, res.stats

    mpps, p50, p99, cs = _timed_loop(
        step, (tab, jnp.asarray(pkt), jnp.asarray(length)), STEPS, B)
    _DIAG["decap_only_mpps"] = round(mpps, 3)
    _DIAG["decap_only_p50_us"] = round(p50, 1)

    # ---- the PRODUCTION path: the same PPPoE data through the FULL
    # fused pipeline (decap -> antispoof -> DHCP -> NAT SNAT -> QoS),
    # i.e. what Engine(pppoe=...) actually runs per batch (round-5
    # integration). The standalone decap number above isolates the op;
    # this one is the deployable cost.
    from bng_tpu.control.nat import NATManager
    from bng_tpu.ops.pipeline import pipeline_step
    from bng_tpu.runtime.engine import AntispoofTables, QoSTables
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.ops.pipeline import PipelineGeom, PipelineTables

    now = 1_753_000_000
    fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=64,
                        cid_nbuckets=64, max_pools=4)
    fp.set_server_config(ac, ip_to_u32("10.0.0.1"))
    n_pub = max(4, -(-N // 1008) + 1)
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1") + i
                                 for i in range(n_pub)],
                     ports_per_subscriber=64,
                     sessions_nbuckets=nb, sub_nat_nbuckets=nb, stash=256)
    sub_ips = ((10 << 24) + 2 + np.arange(N)).astype(np.uint32)
    nat.bulk_allocate_nat(sub_ips, now)
    _, _, ok = nat.bulk_flows(sub_ips, ip_to_u32("8.8.8.8"),
                              np.uint32(5000), np.uint32(53), np.uint32(17),
                              100, now)
    if not ok.all():
        # punted lanes would silently dilute the fused Mpps number
        _DIAG["pppoe_nat_flow_shortfall"] = int((~ok).sum())
    qos = QoSTables(nbuckets=nb)
    qos.bulk_set_subscribers(sub_ips, down_bps=1_000_000_000,
                             up_bps=1_000_000_000)
    spoof = AntispoofTables(nbuckets=256)
    pgeom = PipelineGeom(dhcp=fp.geom, nat=nat.geom, qos=qos.geom,
                         spoof=spoof.geom, pppoe=pp.geom)
    ptables = PipelineTables(
        dhcp=fp.device_tables(), nat=nat.device_tables(),
        qos_up=qos.up.device_state(), qos_down=qos.down.device_state(),
        spoof=spoof.bindings.device_state(),
        spoof_ranges=jnp.asarray(spoof.ranges),
        spoof_config=jnp.asarray(spoof.config),
        pppoe_by_sid=pp.by_sid.device_state(),
        pppoe_by_ip=pp.by_ip.device_state(),
        pppoe_server_mac=jnp.asarray(pp.server_mac))
    fa = jnp.ones((B,), dtype=bool)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fused(tables, pkt, ln):
        res = pipeline_step(tables, pkt, ln, fa, pgeom,
                            jnp.uint32(now), jnp.uint32(0))
        return res.tables, res.verdict, res.out_pkt, res.pppoe_stats

    fmpps, fp50, fp99, fcs = _timed_loop(
        fused, (ptables, jnp.asarray(pkt), jnp.asarray(length)), STEPS, B,
        carry=True)
    _emit("PPPoE+QinQ decap Mpps (config 4)", fmpps, "Mpps", 12.5,
          batch=B, sessions=N, p50_us=round(fp50, 1), p99_us=round(fp99, 1),
          compile_s=round(fcs, 1), fused_pipeline=True,
          includes=["decap", "antispoof", "dhcp", "nat44", "qos"])


def config6_dhcp_fastpath(on_tpu):
    """Diagnostic: the device DHCP fast path STANDALONE at headline scale
    (parse + 3-tier lookup + OFFER compose, no NAT/QoS/antispoof).

    Never measured in isolation before round 3 — if its probe carries the
    narrow-gather pathology at the full table size (PERF_NOTES §2), this
    config names it without the rest of the pipeline in the way.
    """
    import jax
    import jax.numpy as jnp

    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch

    B = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 256))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 5))
    N = int(os.environ.get("BNG_BENCH_SUBS", 1_000_000 if on_tpu else 2_000))
    now = 1_753_000_000
    L = 512

    _mark(f"config6: bulk-inserting {N} subscribers...")
    fp, macs, _ = _build_dhcp_tables(N, now)
    tables = fp.device_tables()

    rng = np.random.default_rng(21)
    pkt = np.zeros((B, L), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    for row in range(B):
        f = _discover_row(macs[int(rng.integers(N))], row + 1)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)
    pkt_d = jax.device_put(jnp.asarray(pkt))
    len_d = jax.device_put(jnp.asarray(length))

    @jax.jit
    def step(tables, pkt, ln):
        par = parse_batch(pkt, ln)
        res = dhcp_fastpath(pkt, ln, par, tables, fp.geom, jnp.uint32(now))
        # out_pkt MUST be an output or XLA DCEs the OFFER compose —
        # the very work this diagnostic exists to measure
        return res.is_reply, res.out_pkt, res.out_len, res.stats

    # sanity: every DISCOVER must hit, or this benchmarks the miss path.
    # This call is also the compile; _timed_loop's first call would read a
    # warm step, so compile_s is timed here.
    t_c = time.time()
    is_reply, _, _, _ = jax.block_until_ready(step(tables, pkt_d, len_d))
    cs = time.time() - t_c
    hit_rate = float(np.asarray(is_reply).sum()) / B
    assert hit_rate > 0.99, f"fastpath hit rate {hit_rate} — table build broken"

    mpps, p50, p99, _ = _timed_loop(step, (tables, pkt_d, len_d), STEPS, B)
    _emit("DHCP fastpath Mpps standalone (config 6)", mpps, "Mpps", 12.5,
          batch=B, subscribers=N, hit_rate=round(hit_rate, 4),
          p50_us=round(p50, 1), p99_us=round(p99, 1), compile_s=round(cs, 1))


def config5_sharded(on_tpu):
    """BASELINE config 5: full pipeline sharded over every visible device."""
    import jax

    from bng_tpu.parallel.sharded import ShardedCluster
    from bng_tpu.utils.net import ip_to_u32

    n = len(jax.devices())
    now = 1_753_000_000
    B_per = int(os.environ.get("BNG_BENCH_BATCH", 8192 if on_tpu else 128))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 5))
    # reference capacity by default on hardware (bpf/maps.h:10): the
    # sharded build splits 1M subscribers by owner shard vectorized
    N = int(os.environ.get("BNG_BENCH_SUBS", 1_000_000 if on_tpu else 1_000))
    sub_nb = 1 << max(10, (N * 2 // 4 // n).bit_length())  # ~50% load/shard
    # garden off: measure the same per-packet work the reference's full
    # BNG does (its walled garden never gates the packet path)
    cl = ShardedCluster(n, batch_per_shard=B_per, sub_nbuckets=sub_nb,
                        max_pools=64, garden_enabled=False)
    cl.set_server_config_all(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    n_pools = max(1, (N >> 16) + 1)
    for pid in range(n_pools):
        cl.add_pool_all(pid + 1, ip_to_u32(f"10.{pid}.0.0") & 0xFFFF0000, 16,
                        ip_to_u32("10.0.0.1"), lease_time=86400)
    _mark(f"config5: bulk-inserting {N} subscribers over {n} shards...")
    macs_u64 = np.arange(N, dtype=np.uint64) + 0x02B500000000
    idx = np.arange(N, dtype=np.uint64)
    cl.add_subscribers_bulk(
        macs_u64, pool_ids=(idx >> np.uint64(16)).astype(np.uint32) + 1,
        ips=((10 << 24) + 2 + idx).astype(np.uint32),
        lease_expiries=np.uint32(now + 86400))
    cl.sync_tables()
    B = n * cl.b
    rng = np.random.default_rng(13)
    pkt = np.zeros((B, 512), dtype=np.uint8)
    length = np.zeros((B,), dtype=np.uint32)
    for row in range(B):
        f = _discover_row(int(macs_u64[int(rng.integers(N))]), 0x2000 + row)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)
    fa = np.ones((B,), dtype=bool)

    _mark(f"config5: compiling sharded step over {n} device(s)...")
    t_c = time.time()
    out = cl.step(pkt, length, fa, now, 0)
    compile_s = time.time() - t_c
    t0 = time.time()
    for k in range(STEPS):
        out = cl.step(pkt, length, fa, now + k + 1, 0)
    dt = time.time() - t0
    mpps = STEPS * B / dt / 1e6
    hit = int(out["dhcp_stats"][1])  # ST_HIT
    _emit(f"Sharded DHCP Mpps over {n} dev (config 5)", mpps, "Mpps",
          12.5 * n, devices=n, batch=B, subscribers=N,
          hits_per_step=hit, compile_s=round(compile_s, 1))


def sharded_serving_bench(on_tpu: bool, n_shards: int) -> None:
    """`--shards N`: the SERVING-PATH aggregate headline (ISSUE 12).

    Where config 5 feeds the sharded step raw host arrays, this drives
    the promoted production loop end to end: a STEERED ring
    (ShardedCluster.make_ring — owner-shard hash + NAT public-IP
    ownership registered), ring-classified batches through
    process_ring_pipelined with depth-2 windows in flight, a mixed
    renewal-DISCOVER + NAT-data workload, and verdict demux back to the
    ring. The aggregate Mpps therefore prices everything the paper's
    ≥100 Mpps target has to pay on a real slice: ring assemble/steer,
    host dispatch, the mesh step, retire + TX drain.

    Ledger identity: `n_shards` rides every emitted line and the cohort
    key (telemetry/ledger.py) so an aggregate 8-shard number can never
    trend against single-device history. The per-shard stage breakdown
    (merged ShardTelemetry histograms) lands in stage_breakdown for the
    per-stage gate, and the run REFUSES to publish if any steered frame
    misteered (missteer_total must be 0 on a ring this bench built)."""
    import jax

    from bng_tpu.parallel.sharded import ShardedCluster
    from bng_tpu.utils.net import ip_to_u32

    n_avail = len(jax.devices())
    if n_avail < n_shards:
        print(json.dumps(_order_line({
            "metric": "Sharded serving Mpps (ring-steered)", "value": 0.0,
            "unit": "Mpps", "vs_baseline": 0.0, "n_shards": n_shards,
            "error": f"need {n_shards} devices, backend has {n_avail}",
            **_DIAG})))
        sys.exit(3)
    now = 1_753_000_000
    B_per = int(os.environ.get("BNG_BENCH_BATCH", 4096 if on_tpu else 64))
    STEPS = int(os.environ.get("BNG_BENCH_STEPS", 100 if on_tpu else 8))
    N = int(os.environ.get("BNG_BENCH_SUBS",
                           1_000_000 if on_tpu else 2_000))
    N_FLOWS = int(os.environ.get("BNG_BENCH_FLOWS", 10_000 if on_tpu
                                 else 256))
    sub_nb = 1 << max(10, (N * 2 // 4 // n_shards).bit_length())
    _mark(f"sharded serving: {n_shards} shards x B={B_per}, {N} subs, "
          f"{N_FLOWS} flows...")
    # port blocks: each shard owns ONE public IP here, so the block
    # width bounds flows/shard at (port_range / width) — size it for
    # the flow count (the reference's CGNAT posture, not 1:1024)
    ppsub = 1 << max(4, ((65535 - 1024) * n_shards
                         // max(1, 2 * N_FLOWS)).bit_length() - 1)
    cl = ShardedCluster(n_shards, batch_per_shard=B_per,
                        sub_nbuckets=sub_nb,
                        nat_sessions_nbuckets=max(256, sub_nb // 4),
                        nat_ports_per_subscriber=min(1024, ppsub),
                        qos_nbuckets=256, spoof_nbuckets=256,
                        max_pools=64, garden_enabled=False)
    cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                             ip_to_u32("10.0.0.1"))
    n_pools = max(1, (N >> 16) + 1)
    for pid in range(n_pools):
        cl.add_pool_all(pid + 1, ip_to_u32(f"10.{pid}.0.0") & 0xFFFF0000,
                        16, ip_to_u32("10.0.0.1"), lease_time=86400)
    macs_u64 = np.arange(N, dtype=np.uint64) + 0x02B500000000
    idx = np.arange(N, dtype=np.uint64)
    sub_ips = ((10 << 24) + 2 + idx).astype(np.uint32)
    cl.add_subscribers_bulk(
        macs_u64, pool_ids=(idx >> np.uint64(16)).astype(np.uint32) + 1,
        ips=sub_ips, lease_expiries=np.uint32(now + 86400))
    # NAT flows on their owner shards (affinity placement): data lanes
    # must FWD on device, never punt
    ext_ip = ip_to_u32("93.184.216.34")
    flow_subs = sub_ips[:N_FLOWS]
    for ip in flow_subs:
        cl.allocate_nat(int(ip), now)  # port block on the owner shard
        _o, flow = cl.handle_new_flow(int(ip), ext_ip, 40000, 443, 17,
                                      600, now)
        assert flow is not None, f"NAT flow setup failed for {ip:#x}"
    cl.sync_tables()

    B = n_shards * cl.b
    ring = cl.make_ring(nframes=1 << max(8, (4 * B).bit_length()),
                        frame_size=2048, depth=max(1024, B_per))
    rng = np.random.default_rng(13)
    from bng_tpu.control import packets

    # preassembled frame pool: half cached-renewal DISCOVERs (device
    # DHCP hits -> TX), half established-flow data (NAT44 -> FWD); the
    # ring classifies and steers each to its owner shard
    POOL = max(256, 2 * B)
    frames = []
    for k in range(POOL):
        if k % 2 == 0:
            frames.append(_discover_row(
                int(macs_u64[int(rng.integers(N))]), 0x4000 + k))
        else:
            src = int(flow_subs[int(rng.integers(len(flow_subs)))])
            frames.append(packets.udp_packet(
                (0x02B500000000 + (src - ((10 << 24) + 2))).to_bytes(6, "big"),
                bytes.fromhex("02aabbccdd01"), src, ext_ip, 40000, 443,
                b"d" * 400))

    def _feed(n_frames: int) -> int:
        fed = 0
        for _ in range(n_frames):
            if not ring.rx_push(frames[(_feed.i) % POOL],
                                from_access=True):
                break
            _feed.i += 1
            fed += 1
        return fed

    _feed.i = 0

    def _drain_tx() -> int:
        got = 0
        while ring.tx_pop() is not None or ring.fwd_pop() is not None:
            got += 1
        return got

    _mark(f"sharded serving: compiling mesh programs over {n_shards} "
          f"device(s)...")
    t_c = time.time()
    _feed(B)
    cl.process_ring_pipelined(ring, now, 0)
    cl.flush_pipeline()
    _drain_tx()
    compile_s = time.time() - t_c

    _mark(f"sharded serving: measuring {STEPS} pipelined windows...")
    processed = 0
    t0 = time.time()
    for k in range(STEPS):
        _feed(B)
        processed += cl.process_ring_pipelined(
            ring, now + k + 1, (k + 1) * 1000)
        _drain_tx()
    processed += cl.flush_pipeline()
    _drain_tx()
    dt = time.time() - t0
    mpps = processed / dt / 1e6

    snap = cl.telemetry.snapshot()
    if snap["missteer_total"] != 0:
        # a steered synthetic ring must place every frame on its owner:
        # a missteer here is a steering bug, not a number to publish
        print(json.dumps(_order_line({
            "metric": "Sharded serving Mpps (ring-steered)", "value": 0.0,
            "unit": "Mpps", "vs_baseline": 0.0, "n_shards": n_shards,
            "error": f"{snap['missteer_total']} missteered frames on a "
                     f"steered ring (steering bug — refusing to publish)",
            "steering": {"missteer_total": snap["missteer_total"],
                         "pass_total": snap["pass_total"]},
            **_DIAG})))
        sys.exit(2)
    stage_breakdown = {s: {"p50_us": h["p50_us"], "p99_us": h["p99_us"],
                           "count": h["count"]}
                       for s, h in snap["merged_stages"].items()}
    _emit("Sharded serving Mpps (ring-steered)", mpps, "Mpps",
          12.5 * n_shards, devices=n_shards, n_shards=n_shards,
          batch=B, subscribers=N, flows=N_FLOWS,
          processed=processed, compile_s=round(compile_s, 1),
          steering={"missteer_total": int(snap["missteer_total"]),
                    "pass_total": int(snap["pass_total"]),
                    "nat_punt_total": int(snap["nat_punt_total"]),
                    "psum_dhcp_hits": int(snap["psum_dhcp_hits"])},
          per_shard_frames=[sh["frames"] for sh in snap["per_shard"]],
          stage_breakdown=stage_breakdown)


def scheduler_bench(on_tpu: bool, checkpoint_interval_s: float = 0.0) -> None:
    """`--scheduler`: latency mode through the tiered scheduler.

    Publishes the quantity the <50us OFFER p99 target actually constrains:
    profiler-isolated per-execution device time of the express-lane
    program (`offer_device_p99_us`), ALONGSIDE the blocked end-to-end
    numbers (`offer_p99_us`) — on the axon tunnel the two differ by the
    ~63ms completion-poll artifact (PERF_NOTES §1), and BENCH JSON that
    only carries blocked numbers cannot support any honest p99 headline.
    Also measures express OFFER latency while the bulk lane is saturated
    (the interleaving claim) and per-lane scheduler stats.
    """
    import jax
    import jax.numpy as jnp

    from bng_tpu.control import packets
    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
    from bng_tpu.runtime.verify import verify_tpu_lowering
    from bng_tpu.utils.profiling import profile_step_durations

    # lowering gate FIRST: scheduler mode refuses to publish latency
    # numbers for programs that do not lower for the target backend
    _mark("scheduler mode: verifying program lowering...")
    results = verify_tpu_lowering(verbose=True, tpu=on_tpu)
    failures = [n for n, e in results if e is not None]
    if failures:
        print(json.dumps({
            "metric": "OFFER p99 device-isolated (scheduler)", "value": 0.0,
            "unit": "us", "vs_baseline": 0.0,
            "error": "scheduler mode refused: lowering verification failed "
                     f"for {failures} — fix the programs or run without "
                     "--scheduler", "failures": failures, **_DIAG}))
        sys.exit(2)

    dev = jax.devices()[0]
    B_BULK = int(os.environ.get("BNG_BENCH_BATCH", 4096 if on_tpu else 256))
    B_EXPR = int(os.environ.get("BNG_SCHED_EXPRESS_BATCH", 64))
    N_SUBS = int(os.environ.get("BNG_BENCH_SUBS", 1_000_000 if on_tpu else 2_000))
    LAT_STEPS = int(os.environ.get("BNG_BENCH_LAT_STEPS", 400 if on_tpu else 30))
    SUSTAIN = int(os.environ.get("BNG_SCHED_SUSTAIN_STEPS", 60 if on_tpu else 6))
    depth = int(os.environ.get("BNG_SCHED_BULK_DEPTH", 2))
    drain_every = int(os.environ.get("BNG_SCHED_DRAIN_EVERY", 4))
    # the scheduler stamps dispatches with the engine's wall clock, so
    # the leases must be built against it (a fixed epoch would read as
    # expired and every warm DISCOVER would miss to the slow path)
    now = int(time.time())
    rng = np.random.default_rng(42)

    t_setup = time.time()
    _mark(f"scheduler bench: {N_SUBS} subscribers, express B={B_EXPR}, "
          f"bulk B={B_BULK} depth={depth}...")
    fp, macs, sub_nb = _build_dhcp_tables(N_SUBS, now)
    nat, flows = _build_nat_flows(max(1000, N_SUBS), max(250, N_SUBS // 4),
                                  now, sub_nat_nbuckets=sub_nb)
    engine = Engine(fp, nat, batch_size=B_BULK, pkt_slot=512)
    # express_aot pinned OFF: this mode's device-isolated metric
    # profiles the FULL `_dhcp_jit` program, so the scheduler must
    # actually serve that architecture — its ledger lines stay in the
    # legacy `jit-full` express_path cohort. The AOT minimal-program
    # lane is measured by `--express-ab`, which emits both cohorts
    # under distinct identities.
    sched = TieredScheduler(engine, SchedulerConfig(
        express_batch=B_EXPR, bulk_batch=B_BULK, bulk_depth=depth,
        drain_every=drain_every, express_aot=False))
    setup_s = time.time() - t_setup

    # optional checkpoint cadence riding the measured loops: the
    # acceptance question is whether quiesce+snapshot+write on a live
    # scheduler moves offer_device_p99_us / express-under-load latency
    ckptr = None
    if checkpoint_interval_s > 0:
        import tempfile

        from bng_tpu.control.statestore import (CheckpointStore,
                                                PeriodicCheckpointer)
        from bng_tpu.runtime.checkpoint import build_checkpoint

        ckpt_dir = (os.environ.get("BNG_CKPT_DIR")
                    or tempfile.mkdtemp(prefix="bng-ckpt-bench-"))
        ckptr = PeriodicCheckpointer(
            CheckpointStore(ckpt_dir),
            lambda seq, t: build_checkpoint(seq, t, engine=engine,
                                            scheduler=sched),
            interval_s=checkpoint_interval_s)
        _mark(f"checkpoint cadence: every {checkpoint_interval_s}s "
              f"-> {ckpt_dir}")

    def discover_batch(base_xid):
        return [_discover_row(macs[int(rng.integers(N_SUBS))], base_xid + k)
                for k in range(B_EXPR)]

    def bulk_batch():
        out = []
        for k in range(B_BULK):
            src_ip, dst_ip, sport = (int(x) for x in
                                     flows[int(rng.integers(len(flows)))])
            out.append(packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src_ip,
                                          dst_ip, sport, 443, b"x" * 180))
        return out

    _mark("compiling express program (scheduler path)...")
    t_c = time.time()
    warm = sched.process(discover_batch(0x8000))
    express_compile_s = time.time() - t_c
    offer_hits = len(warm["tx"])
    _mark(f"express warm: {offer_hits}/{B_EXPR} on-device OFFERs, "
          f"compile {express_compile_s:.1f}s; compiling bulk program...")
    t_c = time.time()
    sched.process(bulk_batch())
    bulk_compile_s = time.time() - t_c

    # ---- blocked end-to-end OFFER latency through the scheduler ----
    _mark(f"blocked OFFER latency: {LAT_STEPS} express batches...")
    llat = []
    for k in range(LAT_STEPS):
        if ckptr is not None:
            ckptr.tick()  # cadence interleaves OUTSIDE the timed window
        frames = discover_batch(0x9000 + k * B_EXPR)
        t1 = time.perf_counter()
        sched.process(frames)
        llat.append(time.perf_counter() - t1)
    llat_us = np.asarray(llat) * 1e6
    offer_p50 = float(np.percentile(llat_us, 50))
    offer_p99 = float(np.percentile(llat_us, 99))

    # ---- profiler-isolated device time of the express program ----
    # a non-donating twin over the live (already express-placed) dhcp
    # chain: the trace's per-execution events carry pure program time,
    # free of host dispatch, demux, and tunnel sync artifacts
    _mark("profiling express program executions...")
    lpkt = np.zeros((B_EXPR, 512), dtype=np.uint8)
    llen = np.zeros((B_EXPR,), dtype=np.uint32)
    for row, f in enumerate(discover_batch(0xA000)):
        lpkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        llen[row] = len(f)
    def place(x):
        return (jax.device_put(x, sched._express_dev)
                if sched._express_dev is not None else x)

    lpkt_d, llen_d = place(jnp.asarray(lpkt)), place(jnp.asarray(llen))
    dtables = engine.tables.dhcp

    @jax.jit
    def dhcp_step(dt, pkt, ln, now_s):
        par = parse_batch(pkt, ln)
        res = dhcp_fastpath(pkt, ln, par, dt, fp.geom, now_s)
        return res.is_reply, res.out_pkt, res.out_len

    jax.block_until_ready(dhcp_step(dtables, lpkt_d, llen_d, jnp.uint32(now)))
    offer_device_p50 = offer_device_p99 = 0.0
    device_source = "none"
    try:
        sd = profile_step_durations(
            lambda: dhcp_step(dtables, lpkt_d, llen_d, jnp.uint32(now)),
            iters=max(20, min(LAT_STEPS, 200)))
        if sd.us:
            offer_device_p50 = sd.percentile(50)
            offer_device_p99 = sd.percentile(99)
            device_source = sd.source
            from bng_tpu.telemetry import spans as _tele

            if _tele.tracer() is not None:  # `device` stage, fenced
                _tele.tracer().observe_many(_tele.DEVICE, sd.us)
        else:
            _DIAG["sched_profile_error"] = "no per-execution events in trace"
    except Exception as e:  # profiling must never sink the benchmark
        _DIAG["sched_profile_error"] = f"{type(e).__name__}: {e}"

    # ---- express latency while the bulk lane is saturated ----
    _mark(f"two-lane sustained load: {SUSTAIN} bulk batches + express trickle...")
    sched.drain_completions()
    t0 = time.time()
    bulk_frames_sent = 0
    express_lat = []

    def drain_express_lat():
        # drain every round: at TPU batch sizes the full run's completion
        # stream would overflow the scheduler's bounded deque and silently
        # evict the EARLIEST express samples, biasing the percentiles
        express_lat.extend(c.latency_s * 1e6 for c in
                           sched.drain_completions() if c.lane == "express")

    for k in range(SUSTAIN):
        for f in bulk_batch():
            sched.submit(f, from_access=True)
        bulk_frames_sent += B_BULK
        for f in discover_batch(0xB000 + k * B_EXPR):
            sched.submit(f, from_access=True)
        sched.poll()
        if ckptr is not None:
            # INSIDE the sustained window: a due save quiesces the live
            # scheduler mid-load, and the express latency samples that
            # straddle it show (or clear) the barrier cost
            ckptr.tick()
        drain_express_lat()
    sched.flush()
    sustain_s = time.time() - t0
    drain_express_lat()
    under_load_p50 = (float(np.percentile(express_lat, 50))
                      if express_lat else 0.0)
    under_load_p99 = (float(np.percentile(express_lat, 99))
                      if express_lat else 0.0)
    bulk_mpps = bulk_frames_sent / sustain_s / 1e6 if sustain_s else 0.0

    line = {
        "metric": "OFFER p99 device-isolated (scheduler)",
        "value": round(offer_device_p99, 1),
        "unit": "us",
        # <50us target (BASELINE.json): >=1.0 beats it; lower latency = higher
        "vs_baseline": round(50.0 / offer_device_p99, 3) if offer_device_p99 else 0.0,
        "offer_p50_us": round(offer_p50, 1),
        "offer_p99_us": round(offer_p99, 1),
        "offer_device_p50_us": round(offer_device_p50, 1),
        "offer_device_p99_us": round(offer_device_p99, 1),
        # default-path key parity (the 50us target's quantity under one
        # name whichever mode produced the artifact)
        "offer_device_only_p50_us": round(offer_device_p50, 1),
        "offer_device_only_p99_us": round(offer_device_p99, 1),
        # explicit cohort identity (matches the unstamped-legacy default:
        # this mode serves and profiles the full program)
        "express_path": "jit-full",
        "device_time_source": device_source,
        "offer_hits_warm": offer_hits,
        "express_under_load_p50_us": round(under_load_p50, 1),
        "express_under_load_p99_us": round(under_load_p99, 1),
        "express_offers_under_load": len(express_lat),
        "bulk_mpps_sustained": round(bulk_mpps, 3),
        "express_batch": B_EXPR,
        "bulk_batch": B_BULK,
        "bulk_depth": depth,
        "drain_every": drain_every,
        "checkpoint_interval_s": checkpoint_interval_s,
        "checkpoints_saved": ckptr.stats["saves"] if ckptr else 0,
        "checkpoint_failures": ckptr.stats["failures"] if ckptr else 0,
        "checkpoint_last_duration_s": (round(ckptr.stats["last_duration_s"], 3)
                                       if ckptr else 0.0),
        "subscribers": N_SUBS,
        "sched": sched.stats_snapshot(),
        "device": str(dev),
        "compile_s": round(express_compile_s + bulk_compile_s, 1),
        "setup_s": round(setup_s, 1),
        **_DIAG,
    }
    from bng_tpu.telemetry import spans as _tele2

    if _tele2.tracer() is not None:
        # scheduler paths are span-instrumented end to end — the full
        # lifecycle breakdown (lane_wait/dispatch/device_wait/slow/reply)
        line["stage_breakdown"] = _tele2.tracer().breakdown()
    _finalize_diag()
    line = _order_line({**line, **{k: v for k, v in _DIAG.items()
                                   if k not in line}})
    print(json.dumps(line))
    _persist(line)


def express_ab_bench(on_tpu: bool) -> None:
    """`--express-ab`: one-flag A/B/C of the express-lane architectures
    — the jit full-program path (`_dhcp_jit`: on-device parse + reply
    compose), the AOT minimal-program path (ISSUE 13: ops/express.py
    admission-extracted descriptors, table probe + verdict block on
    device, host template patch-in), and the devloop ring (ISSUE 18:
    the same AOT architecture served through the k-slot descriptor-ring
    megakernel — one device touch per k admission batches).

    Emits ONE ledger line per cohort, all under the scheduler OFFER
    metric, with `express_path` + `express_loop` joining the cohort
    identity — the trend gate can therefore gate each architecture
    against its own history and REFUSES (rc=3, naming the identities)
    to trend one against another. Each cohort carries:
      - `offer_device_only_p99_us`: profiler-fenced per-execution device
        time of that cohort's express program (the 50us target
        quantity; per-slot amortized for the devloop megakernel);
      - the host-side submit-to-dispatch overhead split the AOT path
        exists to shrink and the devloop ring amortizes k-fold:
        `submit_us_per_batch` (admission incl. descriptor extraction)
        and the `dispatch` stage breakdown (batch close -> device
        enqueue; the devloop pump records it per batch as ring-dispatch
        time / slots, so the histograms stay per-batch comparable);
      - blocked end-to-end OFFER latency through the scheduler.

    Each measured round submits BNG_DEVLOOP_K (default 8) batches per
    cohort before flushing, so the devloop cohort runs FULL rings (its
    steady state) while the per-batch cohorts dispatch k times — the
    per-batch quantities divide by the same k everywhere.
    """
    import jax
    import jax.numpy as jnp

    from bng_tpu.ops.dhcp import NSTATS, dhcp_fastpath
    from bng_tpu.ops.express import XD_WORDS, express_verdicts, parse_express
    from bng_tpu.ops.parse import parse_batch
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
    from bng_tpu.runtime.verify import verify_tpu_lowering
    from bng_tpu.telemetry import FlightRecorder, RecorderConfig
    from bng_tpu.telemetry import spans as tele
    from bng_tpu.utils.profiling import profile_step_durations

    _mark("express A/B: verifying program lowering...")
    results = verify_tpu_lowering(verbose=True, tpu=on_tpu)
    failures = [n for n, e in results if e is not None]
    if failures:
        print(json.dumps({
            "metric": "OFFER p99 device-isolated (scheduler)", "value": 0.0,
            "unit": "us", "vs_baseline": 0.0,
            "error": "express A/B refused: lowering verification failed "
                     f"for {failures}", "failures": failures, **_DIAG}))
        sys.exit(2)

    dev = jax.devices()[0]
    B_EXPR = int(os.environ.get("BNG_SCHED_EXPRESS_BATCH", 64))
    N_SUBS = int(os.environ.get("BNG_BENCH_SUBS",
                                1_000_000 if on_tpu else 2_000))
    LAT_STEPS = int(os.environ.get("BNG_BENCH_LAT_STEPS",
                                   400 if on_tpu else 30))
    # the kill switch must not reach the A/B: a lingering
    # BNG_EXPRESS_AOT=0 would make the "aot-express" stack silently
    # serve jit-full and publish its numbers under the wrong cohort
    # identity — exactly what the rc=3 refusal exists to prevent
    if os.environ.pop("BNG_EXPRESS_AOT", None) == "0":
        _mark("express A/B: ignoring BNG_EXPRESS_AOT=0 (the A/B measures "
              "both architectures by definition)")
    K_LOOP = max(1, int(os.environ.get("BNG_DEVLOOP_K", 8)))
    now = int(time.time())
    rng = np.random.default_rng(42)
    _mark(f"express A/B: {N_SUBS} subscribers, express B={B_EXPR}, "
          f"devloop k={K_LOOP}, {LAT_STEPS} rounds x {K_LOOP} batches "
          f"per cohort...")

    # build ALL stacks up front and INTERLEAVE the measured rounds: the
    # cohorts see the same box noise (GC, sibling load, cache state),
    # so the host-overhead delta is an architecture fact, not a
    # phase-of-run artifact. Each cohort keeps its OWN tracer — the
    # per-stage breakdowns must never mix architectures' samples (that
    # mixing is exactly the comparison the ledger's express_path /
    # express_loop identity forbids).
    stacks: dict[str, dict] = {}
    macs = None
    for path_name, aot, loop in (("jit-full", False, "aot"),
                                 ("aot-express", True, "aot"),
                                 ("devloop", True, "devloop")):
        recorder = FlightRecorder(RecorderConfig())
        recorder.set_backend(jax.default_backend())
        tracer = tele.Tracer(recorder=recorder)
        tele.arm(tracer)
        t_setup = time.time()
        fp, macs, sub_nb = _build_dhcp_tables(N_SUBS, now)
        nat, _flows = _build_nat_flows(1000, 250, now,
                                       sub_nat_nbuckets=sub_nb)
        engine = Engine(fp, nat, batch_size=256, pkt_slot=512)
        sched = TieredScheduler(engine, SchedulerConfig(
            express_batch=B_EXPR, bulk_batch=256, express_aot=aot,
            express_loop=loop, devloop_k=K_LOOP))
        setup_s = time.time() - t_setup
        _mark(f"[{path_name}] compiling + warming...")
        t_c = time.time()
        warm = sched.process(
            [_discover_row(macs[int(rng.integers(N_SUBS))], 0x8000 + k)
             for k in range(B_EXPR)])
        stacks[path_name] = {
            "aot": aot, "loop": loop, "engine": engine, "sched": sched,
            "fp": fp, "tracer": tracer, "setup_s": setup_s,
            "compile_s": time.time() - t_c,
            "offer_hits": len(warm["tx"]),
            "llat": [], "submit_us": [],
        }
        tele.disarm()
        if aot:
            # identity gate: an aot-identity cohort must actually have
            # been SERVED by its program — a compile failure here would
            # file lower-rung measurements under the wrong identity
            ex_snap = sched.stats_snapshot()["express"]
            refused = (not ex_snap["aot_dispatches"]
                       or ex_snap["aot_misses"])
            if loop == "devloop":
                refused = (refused or ex_snap["loop"] != "devloop"
                           or ex_snap.get("fallbacks")
                           or not ex_snap.get("devloop", {}).get(
                               "dispatches"))
            if refused:
                print(json.dumps({
                    "metric": "OFFER p99 device-isolated (scheduler)",
                    "value": 0.0, "unit": "us", "vs_baseline": 0.0,
                    "error": f"express A/B refused: the {path_name} "
                             "stack did not serve via its own program "
                             f"(dispatches={ex_snap['aot_dispatches']}, "
                             f"misses={ex_snap['aot_misses']}, "
                             f"loop={ex_snap['loop']}, fallbacks="
                             f"{ex_snap.get('fallbacks')}) — publishing "
                             "it would mislabel the cohort",
                    **_DIAG}))
                sys.exit(2)

    def discover_batch(base_xid):
        return [_discover_row(macs[int(rng.integers(N_SUBS))],
                              base_xid + k) for k in range(B_EXPR)]

    _mark(f"interleaved measurement: {LAT_STEPS} rounds x {K_LOOP} "
          f"batches per cohort...")
    for k in range(LAT_STEPS):
        # K_LOOP closed batches per round: the devloop cohort runs one
        # FULL ring per round, the per-batch cohorts dispatch K_LOOP
        # times — per-batch figures divide by the same K_LOOP everywhere
        rounds = [discover_batch(0x9000 + (k * K_LOOP + j) * B_EXPR)
                  for j in range(K_LOOP)]
        for path_name, st in stacks.items():
            sched = st["sched"]
            tele.arm(st["tracer"])
            t1 = time.perf_counter()
            for frames in rounds:
                for f in frames:
                    sched.submit(f, from_access=True)
            t2 = time.perf_counter()
            sched.flush()
            t3 = time.perf_counter()
            sched.drain_completions()
            tele.disarm()
            st["submit_us"].append((t2 - t1) * 1e6 / K_LOOP)
            st["llat"].append((t3 - t1) * 1e6 / K_LOOP)

    cohorts: dict[str, dict] = {}
    for path_name, st in stacks.items():
        aot, engine, sched, fp = (st["aot"], st["engine"], st["sched"],
                                  st["fp"])
        tele.arm(st["tracer"])
        dispatch_bd = st["tracer"].breakdown().get("dispatch", {})
        reply_bd = st["tracer"].breakdown().get("reply", {})

        # ---- profiler-isolated device time of THIS cohort's program ----
        # non-donating twins over the live chain (the scheduler_bench
        # discipline): per-execution events carry pure program time
        def place(x):
            return (jax.device_put(x, sched._express_dev)
                    if sched._express_dev is not None else x)

        frames = discover_batch(0xA000)
        dtables = engine.tables.dhcp
        dev_p50 = dev_p99 = 0.0
        dev_scale = 1.0  # devloop: per-ring events amortize to per-slot
        device_source = "none"
        try:
            if st["loop"] == "devloop":
                # the megakernel twin: the k-slot scan over a FULL ring
                # (non-donating, so the profiled arrays survive the
                # repeated executions) — per-execution events carry one
                # RING's device time; amortize to per-slot for the
                # 50us-per-batch target quantity
                desc = np.zeros((B_EXPR, XD_WORDS), dtype=np.uint32)
                for i, f in enumerate(frames):
                    d = parse_express(f)
                    if d is not None:
                        desc[i] = d.words
                ring = np.broadcast_to(
                    desc, (K_LOOP, B_EXPR, XD_WORDS)).copy()
                desc_d = place(jnp.asarray(ring))
                geom = fp.geom
                dev_scale = float(K_LOOP)

                @jax.jit
                def prof_step(dt, dd):
                    def slot(stats, d):
                        res = express_verdicts(dt, d, geom,
                                               jnp.uint32(now))
                        return stats + res.stats, res.block
                    return jax.lax.scan(
                        slot, jnp.zeros((NSTATS,), jnp.uint32), dd)
            elif aot:
                desc = np.zeros((B_EXPR, XD_WORDS), dtype=np.uint32)
                for i, f in enumerate(frames):
                    d = parse_express(f)
                    if d is not None:
                        desc[i] = d.words
                desc_d = place(jnp.asarray(desc))
                geom = fp.geom

                @jax.jit
                def prof_step(dt, dd):
                    res = express_verdicts(dt, dd, geom, jnp.uint32(now))
                    return res.block, res.stats
            else:
                lpkt = np.zeros((B_EXPR, 512), dtype=np.uint8)
                llen = np.zeros((B_EXPR,), dtype=np.uint32)
                for i, f in enumerate(frames):
                    lpkt[i, : len(f)] = np.frombuffer(f, dtype=np.uint8)
                    llen[i] = len(f)
                lpkt_d, llen_d = place(jnp.asarray(lpkt)), place(jnp.asarray(llen))
                geom = fp.geom

                # the batch rides as a real ARGUMENT (a closed-over
                # array is a trace constant XLA would fold the parse
                # and most of the compose against, flattering the full
                # program) — the aot twin's descriptor is an argument
                # for the same reason
                @jax.jit
                def prof_step(dt, dd):
                    pkt_a, len_a = dd
                    par = parse_batch(pkt_a, len_a)
                    res = dhcp_fastpath(pkt_a, len_a, par, dt, geom,
                                        jnp.uint32(now))
                    return res.is_reply, res.out_pkt, res.out_len
                desc_d = (lpkt_d, llen_d)
            jax.block_until_ready(prof_step(dtables, desc_d))
            sd = profile_step_durations(
                lambda: prof_step(dtables, desc_d),
                iters=max(20, min(LAT_STEPS, 200)))
            if sd.us:
                dev_p50 = sd.percentile(50) / dev_scale
                dev_p99 = sd.percentile(99) / dev_scale
                device_source = sd.source
                tele.tracer().observe_many(
                    tele.DEVICE, [u / dev_scale for u in sd.us]
                    if dev_scale != 1.0 else sd.us)
            else:
                _DIAG[f"ab_{path_name}_profile_error"] = "no events in trace"
        except Exception as e:  # profiling must never sink the benchmark
            _DIAG[f"ab_{path_name}_profile_error"] = f"{type(e).__name__}: {e}"

        snap = sched.stats_snapshot()
        llat, submit_us = st["llat"], st["submit_us"]
        line = {
            "metric": "OFFER p99 device-isolated (scheduler)",
            "value": round(dev_p99, 1),
            "unit": "us",
            "vs_baseline": round(50.0 / dev_p99, 3) if dev_p99 else 0.0,
            # the cohort identity the ledger keys on: the gate refuses
            # to trend architectures/loops against each other (rc=3).
            # The devloop cohort IS the aot-express architecture served
            # through the ring loop — path stays aot-express, the loop
            # axis separates it
            "express_path": ("aot-express" if st["loop"] == "devloop"
                             else path_name),
            "express_loop": ("devloop" if st["loop"] == "devloop"
                             else "per-batch"),
            "offer_device_only_p50_us": round(dev_p50, 1),
            "offer_device_only_p99_us": round(dev_p99, 1),
            "device_time_source": device_source,
            "offer_p50_us": round(float(np.percentile(llat, 50)), 1),
            "offer_p99_us": round(float(np.percentile(llat, 99)), 1),
            "submit_us_per_batch": round(float(np.percentile(submit_us, 50)), 1),
            "dispatch_host_p50_us": dispatch_bd.get("p50_us", 0.0),
            "dispatch_host_p99_us": dispatch_bd.get("p99_us", 0.0),
            "reply_host_p50_us": reply_bd.get("p50_us", 0.0),
            "offer_hits_warm": st["offer_hits"],
            "express_batch": B_EXPR,
            "express_aot_misses": snap["express"]["aot_misses"],
            "express_fallbacks": snap["express"]["fallbacks"],
            **({"devloop_k": K_LOOP,
                "devloop": snap["express"].get("devloop")}
               if st["loop"] == "devloop" else {}),
            "subscribers": N_SUBS,
            "sched": snap,
            "device": str(dev),
            "compile_s": round(st["compile_s"], 1),
            "setup_s": round(st["setup_s"], 1),
            **_DIAG,
        }
        # breakdown taken AFTER the profiling pass so the cohort line
        # carries the profiler-fenced `device` stage the SLO gate reads
        line["stage_breakdown"] = st["tracer"].breakdown()
        _finalize_diag()
        line = _order_line({**line, **{k: v for k, v in _DIAG.items()
                                       if k not in line}})
        print(json.dumps(line))
        _persist(line)
        cohorts[path_name] = line
        sched.flush()
        tele.disarm()
        _mark(f"[{path_name}] device p99 {dev_p99:.1f}us, dispatch host "
              f"p50 {dispatch_bd.get('p50_us', 0.0)}us, submit "
              f"{line['submit_us_per_batch']}us/batch")

    # one summary line (its own metric: never a trend point for any
    # cohort) with the host-overhead deltas the AB exists to measure.
    # `devloop_dispatch_reduction_x` is the ISSUE-18 acceptance number:
    # the per-batch host-dispatch stage p50 of the AOT lane over the
    # devloop pump's (ring dispatch / k) — >=4x at k=8 on CPU.
    jit_l, aot_l = cohorts["jit-full"], cohorts["aot-express"]
    dl_l = cohorts["devloop"]
    jit_host = jit_l["submit_us_per_batch"] + jit_l["dispatch_host_p50_us"]
    aot_host = aot_l["submit_us_per_batch"] + aot_l["dispatch_host_p50_us"]
    dl_host = dl_l["submit_us_per_batch"] + dl_l["dispatch_host_p50_us"]
    aot_disp = aot_l["dispatch_host_p50_us"]
    dl_disp = dl_l["dispatch_host_p50_us"]
    summary = _order_line({
        "metric": "express A/B host dispatch overhead delta",
        "value": round(jit_host - aot_host, 1),
        "unit": "us",
        "vs_baseline": round(jit_host / aot_host, 3) if aot_host else 0.0,
        "jit_full_host_us": round(jit_host, 1),
        "aot_express_host_us": round(aot_host, 1),
        "devloop_host_us": round(dl_host, 1),
        "jit_full_device_p99_us": jit_l["offer_device_only_p99_us"],
        "aot_express_device_p99_us": aot_l["offer_device_only_p99_us"],
        "devloop_device_p99_us": dl_l["offer_device_only_p99_us"],
        "devloop_k": K_LOOP,
        "aot_dispatch_p50_us": aot_disp,
        "devloop_dispatch_p50_us": dl_disp,
        "devloop_dispatch_reduction_x": (round(aot_disp / dl_disp, 2)
                                         if dl_disp else 0.0),
        "express_batch": B_EXPR,
        "subscribers": N_SUBS,
        "device": str(dev),
        **_DIAG,
    })
    print(json.dumps(summary))
    _persist(summary)
    _mark(f"devloop dispatch p50 {dl_disp}us/batch vs aot {aot_disp}us "
          f"({summary['devloop_dispatch_reduction_x']}x reduction at "
          f"k={K_LOOP})")


def host_ab_bench(on_tpu: bool) -> None:
    """`--host-ab`: one-flag A/B of the two HOST serving paths (ISSUE
    14) — `scalar` (the original per-frame ring/admission/pack loops)
    vs `vector` (batch-native SoA staging + vectorized classify/steer/
    admit behind BNG_HOST_PATH).

    Drives the production ring loop end to end on BOTH stacks —
    rx_push_batch -> Engine.process_ring_pipelined (assemble ->
    dispatch -> retire/complete) -> reply drain — with an inline
    slow-path fleet on the PASS lanes so the `admit` stage is real.
    Each step alternates an all-control DHCP batch (7/8 known
    subscribers answered on device, 1/8 unknown through admission ->
    worker) with a bulk NAT batch (established flows, FWD on device),
    INTERLEAVED between the cohorts so box noise cancels
    (the --express-ab discipline). Emits ONE ledger line per cohort
    under the host-stage metric with `host_path` joining the cohort
    identity — the gate trends each architecture against its own
    history and refuses (rc=3, naming both paths) to trend one against
    the other. The headline quantity is the SUMMED host-stage p50
    (ring + admit + dispatch + reply): the host-side work a batch pays
    regardless of device speed, whose reciprocal is the host Mpps
    ceiling (`host_mpps_ceiling = batch / summed_p50_us`)."""
    import jax

    from bng_tpu.control import packets
    from bng_tpu.control.admission import AdmissionConfig
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime import hostpath
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.ring import PyRing
    from bng_tpu.telemetry import FlightRecorder, RecorderConfig
    from bng_tpu.telemetry import spans as tele
    from bng_tpu.utils.net import ip_to_u32

    dev = jax.devices()[0]
    B_RING = int(os.environ.get("BNG_HOST_AB_BATCH", 4096))
    N_SUBS = int(os.environ.get("BNG_BENCH_SUBS",
                                1_000_000 if on_tpu else 20_000))
    STEPS = int(os.environ.get("BNG_BENCH_LAT_STEPS",
                               60 if on_tpu else 20))
    HOST_STAGES = ("ring", "admit", "dispatch", "reply")
    now = int(time.time())
    rng = np.random.default_rng(42)
    _mark(f"host A/B: {N_SUBS} subscribers, ring batch {B_RING}, "
          f"{STEPS} interleaved step pairs per cohort...")

    stacks: dict[str, dict] = {}
    macs = flows = None
    for path_name in ("scalar", "vector"):
        # the host path is a construction-time snapshot on every
        # consumer (PyRing/Engine/SlowPathFleet), so the A/B pins it
        # around each stack build and restores the ambient choice
        prev_hp = hostpath.HOST_PATH
        hostpath.HOST_PATH = path_name
        t_setup = time.time()
        try:
            fp, macs, sub_nb = _build_dhcp_tables(N_SUBS, now)
            nat, flows = _build_nat_flows(max(1000, N_SUBS),
                                          max(250, N_SUBS // 4), now,
                                          sub_nat_nbuckets=sub_nb)
            engine = Engine(fp, nat, batch_size=B_RING, pkt_slot=512)
            pm = PoolManager()
            pm.add_pool(Pool(pool_id=1, network=ip_to_u32("172.16.0.0"),
                             prefix_len=16, gateway=ip_to_u32("172.16.0.1"),
                             lease_time=3600))
            fleet = SlowPathFleet(
                FleetSpec.from_pool_manager(bytes.fromhex("02aabbccdd01"),
                                            ip_to_u32("10.0.0.1"), pm),
                n_workers=2, pools=pm, mode="inline",
                admission=AdmissionConfig(
                    inbox_capacity=max(512, 2 * B_RING)))
            engine.slow_path_batch = fleet.handle_batch
            ring = PyRing(nframes=8 * B_RING, frame_size=512,
                          depth=4 * B_RING)
        finally:
            hostpath.HOST_PATH = prev_hp
        assert ring.host_path == path_name and engine.host_path == path_name
        recorder = FlightRecorder(RecorderConfig())
        recorder.set_backend(jax.default_backend())
        stacks[path_name] = {
            "engine": engine, "ring": ring, "fleet": fleet,
            "tracer": tele.Tracer(recorder=recorder),
            "recorder": recorder, "setup_s": time.time() - t_setup,
            "wall_s": 0.0, "frames": 0,
        }

    def dhcp_batch(step: int):
        out = []
        for k in range(B_RING):
            if k % 8 == 7:  # unknown MAC: PASS -> admission -> worker
                mac = (0x02EE00000000 + step * B_RING + k).to_bytes(6, "big")
                out.append(_discover_row(mac, 0xC000 + k))
            else:
                out.append(_discover_row(int(macs[int(rng.integers(N_SUBS))]),
                                         0x9000 + step * B_RING + k))
        return out

    def bulk_batch():
        out = []
        for k in range(B_RING):
            src_ip, dst_ip, sport = (int(x) for x in
                                     flows[int(rng.integers(len(flows)))])
            out.append(packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src_ip,
                                          dst_ip, sport, 443, b"x" * 180))
        return out

    def drive(st, dhcp_frames, bulk_frames) -> int:
        ring, engine = st["ring"], st["engine"]
        n = 0
        ring.rx_push_batch(dhcp_frames)
        n += engine.process_ring_pipelined(ring)
        n += engine.flush_pipeline()
        ring.rx_push_batch(bulk_frames)
        n += engine.process_ring_pipelined(ring)
        n += engine.flush_pipeline()
        ring.tx_pop_batch()
        while ring.fwd_pop() is not None:
            pass
        return n

    # ONE measured corpus, generated once: the device programs' results
    # are captured for exactly these frames at warmup and REPLAYED for
    # every measured step. On XLA:CPU the jitted call executes
    # synchronously in the dispatch thread, so leaving the real program
    # in the measured loop buries the host `dispatch` stage under
    # ~100ms of device compute (the VERDICT r5 host/device conflation,
    # inverted); replaying a warmup capture at the jit boundary makes
    # every measured microsecond HOST work — drain + staging + enqueue
    # + demux — which is precisely the quantity this A/B trends. The
    # slow path (admission -> worker -> reply inject) stays live; the
    # device-time story belongs to configs 2-6 / --express-ab.
    d_frames, b_frames = dhcp_batch(1), bulk_batch()

    _mark("compiling + warming both stacks (device capture)...")
    for st in stacks.values():
        eng = st["engine"]
        for _ in range(2):
            drive(st, d_frames, b_frames)
        cap = {}
        real_step, real_dhcp = eng._step, eng._dhcp_step

        def cap_step(tables, upd, pkt, length, fa, now_s, now_us,
                     _r=real_step, _c=cap):
            res = _r(tables, upd, pkt, length, fa, now_s, now_us)
            _c["bulk"] = jax.tree_util.tree_map(
                np.asarray, res._replace(tables=None))
            return res

        def cap_dhcp(dhcp_tables, upd, pkt, length, now_s,
                     _r=real_dhcp, _c=cap):
            out = _r(dhcp_tables, upd, pkt, length, now_s)
            _c["dhcp"] = tuple(np.asarray(x) for x in out[1:])
            return out

        eng._step, eng._dhcp_step = cap_step, cap_dhcp
        drive(st, d_frames, b_frames)
        assert "bulk" in cap and "dhcp" in cap

        def canned_step(tables, upd, pkt, length, fa, now_s, now_us,
                        _c=cap):
            return _c["bulk"]._replace(tables=tables)

        def canned_dhcp(dhcp_tables, upd, pkt, length, now_s, _c=cap):
            return (dhcp_tables, *_c["dhcp"])

        eng._step, eng._dhcp_step = canned_step, canned_dhcp

    _mark(f"interleaved measurement: {STEPS} step pairs per cohort...")
    for k in range(STEPS):
        for path_name, st in stacks.items():
            tele.arm(st["tracer"])
            t0 = time.perf_counter()
            st["frames"] += drive(st, d_frames, b_frames)
            st["wall_s"] += time.perf_counter() - t0
            tele.disarm()

    cohorts: dict[str, dict] = {}
    for path_name, st in stacks.items():
        bd = st["tracer"].breakdown()
        host_p50 = {s: bd.get(s, {}).get("p50_us", 0.0)
                    for s in HOST_STAGES}
        host_p99 = {s: bd.get(s, {}).get("p99_us", 0.0)
                    for s in HOST_STAGES}
        host_sum_p50 = round(sum(host_p50.values()), 1)
        host_sum_p99 = round(sum(host_p99.values()), 1)
        wall_mpps = (st["frames"] / st["wall_s"] / 1e6
                     if st["wall_s"] else 0.0)
        line = {
            "metric": "host serving loop p50 (ring+admit+dispatch+reply)",
            "value": host_sum_p50,
            "unit": "us",
            "vs_baseline": 0.0,  # filled below: scalar_sum / this_sum
            # the cohort identity the ledger keys on: the gate refuses
            # to trend the two host architectures against each other
            "host_path": path_name,
            "host_stage_sum_p50_us": host_sum_p50,
            "host_stage_sum_p99_us": host_sum_p99,
            # the host-side throughput ceiling this batch size implies:
            # one batch costs host_sum_p50 us of host work, so the host
            # alone caps the loop at batch/host-seconds regardless of
            # how fast the chips get
            "host_mpps_ceiling": (round(B_RING / host_sum_p50, 3)
                                  if host_sum_p50 else 0.0),
            "wall_mpps": round(wall_mpps, 3),
            **{f"{s}_p50_us": host_p50[s] for s in HOST_STAGES},
            **{f"{s}_p99_us": host_p99[s] for s in HOST_STAGES},
            "frames": st["frames"],
            "batch": B_RING,
            "subscribers": N_SUBS,
            "slowpath_admitted":
                st["fleet"].admission.stats_snapshot()["admitted"],
            "ring_stats": st["ring"].stats(),
            "device": str(dev),
            "setup_s": round(st["setup_s"], 1),
            **_DIAG,
        }
        line["stage_breakdown"] = bd
        cohorts[path_name] = line

    # identity gate: both cohorts must have run the ring loop they
    # claim (a silent fallback would publish mislabeled numbers)
    sc, ve = cohorts["scalar"], cohorts["vector"]
    for path_name, line in cohorts.items():
        base = sc["host_stage_sum_p50_us"]
        line["vs_baseline"] = (round(base / line["host_stage_sum_p50_us"], 3)
                               if line["host_stage_sum_p50_us"] else 0.0)
        _finalize_diag()
        out = _order_line({**line, **{k: v for k, v in _DIAG.items()
                                      if k not in line}})
        print(json.dumps(out))
        _persist(out)
        _mark(f"[{path_name}] host stages p50 "
              + " ".join(f"{s}={line[f'{s}_p50_us']}us"
                         for s in HOST_STAGES)
              + f" sum={line['host_stage_sum_p50_us']}us "
              f"ceiling={line['host_mpps_ceiling']}Mpps "
              f"wall={line['wall_mpps']}Mpps")

    speedup = (sc["host_stage_sum_p50_us"] / ve["host_stage_sum_p50_us"]
               if ve["host_stage_sum_p50_us"] else 0.0)
    summary = _order_line({
        "metric": "host A/B vector speedup (summed host-stage p50)",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 2.0, 3),  # ISSUE 14 exit: >=2x
        "scalar_host_sum_p50_us": sc["host_stage_sum_p50_us"],
        "vector_host_sum_p50_us": ve["host_stage_sum_p50_us"],
        "scalar_host_mpps_ceiling": sc["host_mpps_ceiling"],
        "vector_host_mpps_ceiling": ve["host_mpps_ceiling"],
        "scalar_wall_mpps": sc["wall_mpps"],
        "vector_wall_mpps": ve["wall_mpps"],
        "batch": B_RING,
        "subscribers": N_SUBS,
        "device": str(dev),
        **_DIAG,
    })
    print(json.dumps(summary))
    _persist(summary)


def wire_ab_bench(on_tpu: bool) -> None:
    """`--wire-ab`: one-flag A/B of the two WIRE PUMP implementations
    (ISSUE 15) — `scalar` (the original per-frame ctypes loop with the
    copy-mode normalizing memmove) vs `vector` (array-in/array-out over
    the native batch verbs, headroom-aware descriptors) behind
    BNG_WIRE_PUMP.

    Drives the full wire loop on the memory rung — far-end inject ->
    kernel rings (SimKernelRings over the REAL UMEM, copy-mode headroom
    shape) -> WirePump -> NativeRing -> batch assemble/complete ->
    WirePump -> far-end drain — steady-state pipelined so every
    measured pump round moves a full batch in BOTH directions. The
    ring consumer is a host-only reflector (assemble -> verdict TX ->
    complete): the wire_rx/wire_tx stages lap only inside pump(), so
    device compute would add wall time without touching the measured
    quantity — the --host-ab replay discipline taken to its limit.
    Steps INTERLEAVE between the cohorts so box noise cancels (the
    --express-ab discipline). Emits ONE ledger line per cohort under
    the wire-stage metric with `wire_pump` joining the cohort identity
    — the gate trends each pump against its own history and refuses
    (rc=3, naming both paths) to trend one against the other. The
    headline quantity is the SUMMED wire-stage p50 (wire_rx + wire_tx):
    the kernel<->UMEM cost every batch pays regardless of chip speed,
    whose reciprocal is the wire Mpps ceiling
    (`wire_mpps_ceiling = batch / summed_p50_us`)."""
    from bng_tpu.control import packets
    from bng_tpu.runtime import xsk as xsk_mod
    from bng_tpu.runtime.ring import VERDICT_TX, NativeRing
    from bng_tpu.telemetry import FlightRecorder, RecorderConfig
    from bng_tpu.telemetry import spans as tele

    B = int(os.environ.get("BNG_WIRE_AB_BATCH", 2048))
    STEPS = int(os.environ.get("BNG_BENCH_LAT_STEPS",
                               60 if on_tpu else 30))
    WARMUP = 3
    HEADROOM = 256  # the copy-mode RX shape: scalar pays the per-frame
    #                 normalizing memmove here, vector submits as-is
    SLOT = 512
    WIRE_STAGES = ("wire_rx", "wire_tx")
    nframes = 1 << (8 * B - 1).bit_length()
    kring = 1 << (2 * B - 1).bit_length()
    _mark(f"wire A/B: batch {B}, {STEPS} interleaved steps per cohort, "
          f"copy-mode headroom {HEADROOM}...")

    # one shared corpus: established-flow UDP data frames (classify ->
    # data path, steer -> shard 0), built once and injected identically
    # into both cohorts' far ends
    rng = np.random.default_rng(42)
    frames = [packets.udp_packet(
        b"\x02" * 6, b"\x04" * 6, 0x0A000000 + int(rng.integers(1 << 16)),
        0xC6336401, 1024 + k % 40000, 443, b"x" * 180)
        for k in range(B)]

    stacks: dict[str, dict] = {}
    for path_name in ("scalar", "vector"):
        ring = NativeRing(nframes=nframes, frame_size=2048, depth=kring)
        kern = xsk_mod.SimKernelRings(ring, headroom=HEADROOM,
                                      ring_size=kring)
        pump = xsk_mod.WirePump(ring, kern, path=path_name)
        recorder = FlightRecorder(RecorderConfig())
        out = np.zeros((B, SLOT), dtype=np.uint8)
        out_len = np.zeros(B, dtype=np.uint32)
        out_flags = np.zeros(B, dtype=np.uint32)
        verdict = np.full(B, VERDICT_TX, dtype=np.uint8)
        stacks[path_name] = {
            "ring": ring, "kern": kern, "pump": pump,
            "tracer": tele.Tracer(recorder=recorder),
            "out": out, "out_len": out_len, "out_flags": out_flags,
            "verdict": verdict, "wall_s": 0.0, "replies": 0,
        }

    def reflect(st) -> int:
        """Host-only ring consumer: assemble -> all-TX -> complete
        (replies echo the request bytes; the wire loop's cost under
        test is the PUMP, not the verdict producer)."""
        ring = st["ring"]
        n = ring.assemble(st["out"], st["out_len"], st["out_flags"])
        if n:
            ring.complete(st["verdict"][:n], st["out"][:n],
                          st["out_len"][:n], n)
        return n

    # prime the pipeline: after warmup every step's pump round moves B
    # frames in (this step's inject) AND B frames out (last step's
    # reflected verdicts) — full-duplex laps, unimodal distributions
    for st in stacks.values():
        for _ in range(WARMUP):
            st["kern"].inject_many(frames)
            st["pump"].pump(budget=B)
            st["kern"].deliver()  # first rounds: fill was empty at inject
            st["pump"].pump(budget=B)
            reflect(st)
            st["kern"].drain_egress()

    _mark(f"interleaved measurement: {STEPS} steps per cohort...")
    for _k in range(STEPS):
        for path_name, st in stacks.items():
            st["kern"].inject_many(frames)  # far-end NIC work: unmeasured
            tele.arm(st["tracer"])
            t0 = time.perf_counter()
            st["pump"].pump(budget=B)
            st["wall_s"] += time.perf_counter() - t0
            tele.disarm()
            st["replies"] += len(st["kern"].drain_egress())
            reflect(st)

    cohorts: dict[str, dict] = {}
    for path_name, st in stacks.items():
        # identity gate: the cohort must have run the pump it claims
        # (a silent scalar fallback would publish mislabeled numbers)
        assert st["pump"].last_path == path_name, (
            f"cohort {path_name!r} last ran {st['pump'].last_path!r}")
        bd = st["tracer"].breakdown()
        p50 = {s: bd.get(s, {}).get("p50_us", 0.0) for s in WIRE_STAGES}
        p99 = {s: bd.get(s, {}).get("p99_us", 0.0) for s in WIRE_STAGES}
        sum_p50 = round(sum(p50.values()), 1)
        sum_p99 = round(sum(p99.values()), 1)
        # 2B frames (B rx + B tx) per measured pump round
        wall_mpps = (2 * B * STEPS / st["wall_s"] / 1e6
                     if st["wall_s"] else 0.0)
        line = {
            "metric": "wire pump p50 (wire_rx+wire_tx)",
            "value": sum_p50,
            "unit": "us",
            "vs_baseline": 0.0,  # filled below: scalar_sum / this_sum
            # the cohort identity the ledger keys on: the gate refuses
            # to trend the two pump implementations against each other
            "wire_pump": path_name,
            "wire_rung": "memory",
            "wire_stage_sum_p50_us": sum_p50,
            "wire_stage_sum_p99_us": sum_p99,
            # the wire-side throughput ceiling this batch size implies:
            # one full-duplex batch costs sum_p50 us of pump work, so
            # the pump alone caps the wire loop at batch/pump-seconds
            # regardless of how fast the chips and the host path behind
            # it are
            "wire_mpps_ceiling": (round(B / sum_p50, 3) if sum_p50
                                  else 0.0),
            "wall_mpps": round(wall_mpps, 3),
            **{f"{s}_p50_us": p50[s] for s in WIRE_STAGES},
            **{f"{s}_p99_us": p99[s] for s in WIRE_STAGES},
            "pump_stats": dict(st["pump"].pump_stats),
            "replies": st["replies"],
            "batch": B,
            "headroom": HEADROOM,
            "ring_stats": st["ring"].stats(),
            **_DIAG,
        }
        line["stage_breakdown"] = bd
        cohorts[path_name] = line

    sc, ve = cohorts["scalar"], cohorts["vector"]
    # same deterministic workload over the same verbs: the two pumps'
    # frame accounting must agree exactly (the bit-identity corpus in
    # tests/test_wire_pump.py pins the per-frame cases; this is the
    # aggregate check at bench scale)
    stats_match = sc["pump_stats"] == ve["pump_stats"]
    if not stats_match:
        _mark(f"WARNING: cohort pump_stats diverge: scalar="
              f"{sc['pump_stats']} vector={ve['pump_stats']}")
    for path_name, line in cohorts.items():
        base = sc["wire_stage_sum_p50_us"]
        line["vs_baseline"] = (round(base / line["wire_stage_sum_p50_us"], 3)
                               if line["wire_stage_sum_p50_us"] else 0.0)
        line["pump_stats_match"] = stats_match
        _finalize_diag()
        out = _order_line({**line, **{k: v for k, v in _DIAG.items()
                                      if k not in line}})
        print(json.dumps(out))
        _persist(out)
        _mark(f"[{path_name}] wire stages p50 "
              + " ".join(f"{s}={line[f'{s}_p50_us']}us"
                         for s in WIRE_STAGES)
              + f" sum={line['wire_stage_sum_p50_us']}us "
              f"ceiling={line['wire_mpps_ceiling']}Mpps "
              f"wall={line['wall_mpps']}Mpps")

    speedup = (sc["wire_stage_sum_p50_us"] / ve["wire_stage_sum_p50_us"]
               if ve["wire_stage_sum_p50_us"] else 0.0)
    summary = _order_line({
        "metric": "wire A/B vector speedup (summed wire-stage p50)",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 2.0, 3),  # ISSUE 15 exit: >=2x
        "scalar_wire_sum_p50_us": sc["wire_stage_sum_p50_us"],
        "vector_wire_sum_p50_us": ve["wire_stage_sum_p50_us"],
        "scalar_wire_mpps_ceiling": sc["wire_mpps_ceiling"],
        "vector_wire_mpps_ceiling": ve["wire_mpps_ceiling"],
        "scalar_wall_mpps": sc["wall_mpps"],
        "vector_wall_mpps": ve["wall_mpps"],
        "pump_stats_match": stats_match,
        "batch": B,
        "headroom": HEADROOM,
        **_DIAG,
    })
    print(json.dumps(summary))
    _persist(summary)
    for st in stacks.values():
        st["ring"].close()


def autotune_mode(on_tpu: bool, dry_run: bool = False) -> None:
    """`--autotune`: stage-breakdown-driven sweep of batch geometry
    (B=256..16384) x bulk pipeline depth (2..8) x table impl (ISSUE 11).

    Dapper discipline: the objective is the MEASURED stage, not a guess
    — each point's `device` stage comes from the profiler-fenced
    per-execution distribution (profile_step_durations, block inside
    the capture), the throughput comes from a depth-pipelined window at
    that point's depth, and the SLO registry's `device` budget decides
    eligibility (slo.evaluate over exactly that spec). Every point is
    appended to the schema'd ledger impl-keyed, so `bng perf gate`
    inherits the new cohorts; the best point prints as the run's JSON.

    --dry-run (make verify-kernels): tiny geometry, DHCP-only program,
    temp ledger — validates the sweep/ledger plumbing in seconds with
    no hardware and without touching the repo's history.
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    import bng_tpu.ops.table as table_mod
    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch
    from bng_tpu.telemetry import ledger, slo
    from bng_tpu.telemetry.ledger import environment_fingerprint
    from bng_tpu.utils.profiling import profile_step_durations

    def _env_ints(name, default):
        raw = os.environ.get(name)
        return [int(x) for x in raw.split(",")] if raw else default

    if dry_run:
        batches, depths, steps, n_subs = [256], [2], 3, 2_000
        program = "dhcp"
        ledger_path = os.path.join(tempfile.mkdtemp(prefix="bng-autotune-"),
                                   "autotune.jsonl")
    else:
        batches = _env_ints("BNG_AUTOTUNE_BATCHES",
                            [256, 1024, 4096, 8192, 16384] if on_tpu
                            else [256, 512])
        depths = _env_ints("BNG_AUTOTUNE_DEPTHS",
                           [2, 4, 8] if on_tpu else [2])
        steps = int(os.environ.get("BNG_AUTOTUNE_STEPS",
                                   40 if on_tpu else 4))
        n_subs = int(os.environ.get("BNG_BENCH_SUBS",
                                    100_000 if on_tpu else 2_000))
        program = os.environ.get("BNG_AUTOTUNE_PROGRAM", "fused")
        ledger_path = ledger.default_ledger_path()
    impls = ("xla", "pallas")
    now = 1_753_000_000
    dev_spec = next(s for s in slo.DEFAULT_SLOS if s.stage == "device")

    _mark(f"autotune: program={program} B={batches} depth={depths} "
          f"impls={impls} subs={n_subs} -> {ledger_path}")
    t_setup = time.time()
    fp, macs, sub_nb = _build_dhcp_tables(n_subs, now)
    nat = None
    if program == "fused":
        nat, flows = _build_nat_flows(n_subs, max(1, n_subs // 4), now,
                                      sub_nat_nbuckets=sub_nb)
    rng = np.random.default_rng(23)
    Bmax = max(batches)
    L = 512
    pkt = np.zeros((Bmax, L), dtype=np.uint8)
    length = np.zeros((Bmax,), dtype=np.uint32)
    n_dhcp = Bmax if program == "dhcp" else Bmax // 5
    for row in range(Bmax):
        if row < n_dhcp:
            f = _discover_row(macs[int(rng.integers(n_subs))], 0x4000 + row)
        else:
            from bng_tpu.control import packets

            src_ip, dst_ip, sport = (int(x) for x in
                                     flows[int(rng.integers(len(flows)))])
            f = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, src_ip, dst_ip,
                                   sport, 443, b"x" * 180)
        pkt[row, : len(f)] = np.frombuffer(f, dtype=np.uint8)
        length[row] = len(f)
    _mark(f"autotune setup {time.time() - t_setup:.1f}s")

    points: list[dict] = []
    for impl in impls:
        for B in batches:
            pkt_d = jax.device_put(jnp.asarray(pkt[:B]))
            len_d = jax.device_put(jnp.asarray(length[:B]))
            try:
                if program == "fused":
                    from bng_tpu.ops.pipeline import (PipelineGeom,
                                                      PipelineTables,
                                                      pipeline_step)
                    from bng_tpu.runtime.engine import (AntispoofTables,
                                                        QoSTables)

                    qos = QoSTables(nbuckets=1 << 10)
                    spoof = AntispoofTables(nbuckets=1 << 10)
                    geom = PipelineGeom(dhcp=fp.geom, nat=nat.geom,
                                        qos=qos.geom, spoof=spoof.geom)
                    fa_d = jax.device_put(jnp.ones((B,), dtype=bool))

                    # NON-donating: the sweep probes many (impl, B)
                    # points over ONE table build; donation would
                    # consume it at the first point
                    @jax.jit
                    def step_fn(tables, pkt, ln, _impl=impl, _geom=geom,
                                _fa=fa_d):
                        with table_mod.forced_impl(_impl):
                            res = pipeline_step(tables, pkt, ln, _fa, _geom,
                                                jnp.uint32(now),
                                                jnp.uint32(1))
                        return res.verdict

                    tables = PipelineTables(
                        dhcp=fp.device_tables(), nat=nat.device_tables(),
                        qos_up=qos.up.device_state(),
                        qos_down=qos.down.device_state(),
                        spoof=spoof.bindings.device_state(),
                        spoof_ranges=jnp.asarray(spoof.ranges),
                        spoof_config=jnp.asarray(spoof.config))
                else:
                    @jax.jit
                    def step_fn(tables, pkt, ln, _impl=impl):
                        with table_mod.forced_impl(_impl):
                            par = parse_batch(pkt, ln)
                            res = dhcp_fastpath(pkt, ln, par, tables,
                                                fp.geom, jnp.uint32(now))
                        return res.is_reply

                    tables = fp.device_tables()

                t_c = time.time()
                jax.block_until_ready(step_fn(tables, pkt_d, len_d))
                compile_s = time.time() - t_c
                sd = profile_step_durations(
                    lambda: step_fn(tables, pkt_d, len_d),
                    iters=max(10, min(steps * 4, 100)))
                dev_stage = None
                if sd.us:
                    dev_stage = {
                        "count": len(sd.us),
                        "p50_us": round(sd.percentile(50), 1),
                        "p99_us": round(sd.percentile(99), 1)}
            except Exception as e:  # one point failing never sinks the sweep
                _mark(f"autotune point impl={impl} B={B} failed: "
                      f"{type(e).__name__}: {e}")
                _DIAG[f"autotune_{impl}_{B}_error"] = f"{type(e).__name__}: {e}"
                continue

            for depth in depths:
                t0 = time.perf_counter()
                vs = []
                rounds = max(steps, depth + 1)
                for k in range(rounds):
                    out = step_fn(tables, pkt_d, len_d)
                    vs.append(out)
                    if len(vs) > depth:  # keep `depth` steps in flight
                        vs.pop(0).block_until_ready()
                jax.block_until_ready(vs)
                per_step = (time.perf_counter() - t0) / rounds
                mpps = B / per_step / 1e6
                verdict = (slo.evaluate({"device": dev_stage},
                                        slos=(dev_spec,))
                           if dev_stage else
                           {"ok": False, "breaches": ["device:missing"]})
                point = {
                    "metric": "autotune sweep point",
                    "value": round(mpps, 3),
                    "unit": "Mpps",
                    "vs_baseline": round(mpps / 12.5, 4),
                    "program": program,
                    "batch": B,
                    "depth": depth,
                    "table_impl": impl,
                    "subscribers": n_subs,
                    "pipelined_us_per_step": round(per_step * 1e6, 1),
                    "compile_s": round(compile_s, 1),
                    "stage_breakdown": ({"device": dev_stage}
                                        if dev_stage else {}),
                    "device_time_source": sd.source if sd.us else "none",
                    "slo": verdict,
                    "env": environment_fingerprint(),
                    **({"backend_fallback": _DIAG["backend_fallback"]}
                       if "backend_fallback" in _DIAG else {}),
                }
                try:
                    ledger.append(ledger_path, point)
                except OSError:
                    pass  # read-only checkout: stdout carries the result
                points.append(point)
                _mark(f"point impl={impl} B={B} depth={depth}: "
                      f"{mpps:.3f} Mpps, device p99 "
                      f"{dev_stage['p99_us'] if dev_stage else '?'}us, "
                      f"slo_ok={verdict['ok']}")

    if not points:
        print(_error_line(0, "autotune: every sweep point failed"))
        sys.exit(1)
    # objective: max throughput among SLO-eligible points (the device
    # stage under its budget); if nothing is eligible, best raw point
    # ships flagged — an honest answer beats a vacuous one
    eligible = [p for p in points if p["slo"]["ok"]]
    pool = eligible or points
    best = max(pool, key=lambda p: p["value"])
    if table_mod.TABLE_IMPL == "auto":
        table_mod.set_auto_choice(best["table_impl"])
    _finalize_diag()
    line = _order_line({
        "metric": "autotune best point",
        "value": best["value"],
        "unit": "Mpps",
        "vs_baseline": best["vs_baseline"],
        "best": {k: best[k] for k in ("program", "batch", "depth",
                                      "table_impl",
                                      "pipelined_us_per_step", "slo")},
        "points": len(points),
        "slo_eligible": len(eligible),
        "dry_run": dry_run,
        "autotune_ledger": ledger_path,
        **_DIAG,
        # the BEST point's impl, after _DIAG so the per-run stamp (the
        # pre-sweep resolution) cannot shadow the sweep's answer
        "table_impl": best["table_impl"],
    })
    print(json.dumps(line))
    if not dry_run:
        _persist(line)


_CONFIG_METRICS = {
    0: ("Mpps/chip DHCP+NAT44 fast path", "Mpps"),
    1: ("DHCP slow-path req/s (config 1)", "req/s"),
    2: ("NAT44 Mpps @100k flows (config 2)", "Mpps"),
    3: ("QoS token-bucket Mpps @10k subs (config 3)", "Mpps"),
    4: ("PPPoE+QinQ decap Mpps (config 4)", "Mpps"),
    5: ("Sharded DHCP Mpps (config 5)", "Mpps"),
    6: ("DHCP fastpath Mpps standalone (config 6)", "Mpps"),
}


def _error_line(config: int, err: str) -> str:
    metric, unit = _CONFIG_METRICS.get(config, _CONFIG_METRICS[0])
    return json.dumps(_order_line({"metric": metric, "value": 0.0,
                                   "unit": unit, "vs_baseline": 0.0,
                                   "config": config, "error": err,
                                   **_DIAG}))


def _run_lowering_gate(strict: bool) -> None:
    """TPU-lowering pre-step (verifier-harness analog; see runtime/verify.py).

    strict=True (--verify-lowering): emit a JSON verdict line, exit 1 on any
    failure. strict=False (auto pre-step before the headline): record
    failures in the diag fields and continue.
    """
    from bng_tpu.runtime.verify import verify_tpu_lowering

    _mark("TPU-lowering gate: compiling hot programs for the TPU target...")
    results = verify_tpu_lowering(verbose=True)
    failures = [n for n, e in results if e is not None]
    if strict:
        print(json.dumps({
            "metric": "TPU-lowering gate", "value": float(len(failures) == 0),
            "unit": "pass", "vs_baseline": float(len(failures) == 0),
            "checked": [n for n, _ in results], "failures": failures,
        }))
        sys.exit(1 if failures else 0)
    if failures:
        _DIAG["lowering_failures"] = failures
        _mark(f"lowering gate FAILURES (continuing): {failures}")


def _child_dispatch(config: int, verify_lowering: bool = False,
                    scheduler: bool = False,
                    checkpoint_interval_s: float = 0.0,
                    require_tpu: bool = False,
                    autotune: bool = False,
                    autotune_dry_run: bool = False,
                    shards: int = 0,
                    express_ab: bool = False,
                    host_ab: bool = False,
                    wire_ab: bool = False) -> None:
    """Run one benchmark config in this process (the supervised child)."""
    try:
        # environment fingerprint (device kind / jaxlib / hostname) on
        # EVERY emitted JSON line — today `device`+`compile_s` is all a
        # reader gets, and the perf gate's cohorts key on this identity.
        # Stamped before config 1 (which never probes a backend: the
        # fingerprint must not trigger jax init) and refreshed after the
        # guarded probe once the device identity is known.
        from bng_tpu.telemetry.ledger import environment_fingerprint

        _DIAG["env"] = environment_fingerprint()
        if config == 1 and not verify_lowering and not scheduler:
            config1_dhcp_slowpath()
            return

        # Guarded backend init (never crash): probe the axon TPU plugin in a
        # subprocess with a timeout; on failure, fall back to a hermetic CPU
        # backend and record the diagnostic in the JSON line. Round 1 shipped
        # both failure modes as artifacts (BENCH_r01 rc=1, MULTICHIP rc=124).
        from bng_tpu.utils.jaxenv import guarded_backend, tunnel_precheck

        window = _probe_window()
        if window > 0:
            # cheap relay/tunnel health check BEFORE committing the 900s
            # window: a fast "up" skips straight to the real probe; a
            # fast "down" is recorded and the window runs with BACKOFF
            # cadence (poll often early — tunnels usually flap back in
            # under a minute — without burning the window on a dead one)
            up, diag = tunnel_precheck(
                float(os.environ.get("BNG_BENCH_PRECHECK_TIMEOUT", 20)))
            _DIAG["tunnel_precheck"] = "up" if up else f"down: {diag}"
            _mark(f"tunnel precheck: {_DIAG['tunnel_precheck']}")
        _mark("probing accelerator availability"
              + (f" (capture-on-return window {window:.0f}s)..." if window
                 else "..."))
        # `tries` stays an honest upper bound in window mode too (an
        # explicit BNG_BENCH_PROBE_TRIES=1 means single-shot regardless of
        # the window); the default just stops being the binding constraint
        # when a capture-on-return window is active.
        platform, err = guarded_backend(
            tries=int(os.environ.get("BNG_BENCH_PROBE_TRIES",
                                     999 if window > 0 else 2)),
            probe_timeout_s=float(os.environ.get("BNG_BENCH_PROBE_TIMEOUT", 150)),
            retry_sleep_s=float(os.environ.get(
                "BNG_BENCH_PROBE_SLEEP", 15 if window > 0 else 45)),
            window_s=window,
            backoff=float(os.environ.get(
                "BNG_BENCH_PROBE_BACKOFF", 1.6 if window > 0 else 1.0)),
            # --shards on a chipless box: the CPU fallback mesh must be
            # wide enough for the requested shard count (forced host
            # devices, the tier-1 posture)
            cpu_devices=max(8, shards),
        )
        on_tpu = platform not in ("cpu",)
        _mark(f"backend: {platform}" + (f" (fallback: {err})" if err else ""))
        # table-probe impl (ISSUE 11): resolve auto by racing both impls
        # post-compile, then stamp the CHOICE on every emitted line —
        # a Pallas number must never read as an XLA one (the ledger
        # cohorts key on it, rc=3 on cross-impl comparison). The
        # autotune sweep IS the race at full fidelity (every point runs
        # under an explicit forced impl and the best point pins the auto
        # choice), so --autotune skips the standalone probe race rather
        # than paying two throwaway compiles for an answer it overwrites.
        if autotune:
            import bng_tpu.ops.table as _table_mod

            _DIAG["table_impl"] = _table_mod.current_impl_label()
        else:
            _DIAG["table_impl"] = _pick_table_impl(on_tpu)
        _DIAG["env"] = environment_fingerprint()  # now with device identity
        if err:
            _DIAG["backend_fallback"] = "cpu"
            _DIAG["backend_error"] = err
        if require_tpu and not on_tpu:
            # CI gate: refuse to publish CPU numbers as headlines — emit
            # the flagged error line and exit nonzero (rc=3)
            _DIAG.setdefault("backend_fallback", "cpu")
            _DIAG.setdefault("backend_error", err or "no accelerator")
            print(_error_line(config,
                              "--require-tpu: accelerator unavailable, "
                              "refusing to run on CPU"))
            sys.exit(3)
        # arm the telemetry tracer for the run: stage_breakdown in the
        # emitted JSON, and the flight recorder that must dump on a
        # backend fallback (_finalize_diag)
        from bng_tpu.telemetry import (FlightRecorder, RecorderConfig,
                                       spans as tele)

        recorder = FlightRecorder(RecorderConfig())
        recorder.set_backend(platform)
        tele.arm(tele.Tracer(recorder=recorder))
        # persistent XLA compile cache: repeat bench runs skip the
        # minutes-long compile phase (verdict weakness 5; BNG_JAX_CACHE_DIR=0 off)
        from bng_tpu.utils.jaxenv import enable_compilation_cache

        cache_dir = enable_compilation_cache()
        if cache_dir:
            _mark(f"compilation cache: {cache_dir}")
        if shards > 1:
            # cohort identity: EVERY line this run emits (result or
            # error) carries the shard count (ledger.n_shards keys on it)
            _DIAG["n_shards"] = shards
            sharded_serving_bench(on_tpu, shards)
            return
        if autotune:
            autotune_mode(on_tpu, dry_run=autotune_dry_run)
            return
        if express_ab:
            express_ab_bench(on_tpu)
            return
        if host_ab:
            host_ab_bench(on_tpu)
            return
        if wire_ab:
            wire_ab_bench(on_tpu)
            return
        if scheduler:
            scheduler_bench(on_tpu, checkpoint_interval_s=checkpoint_interval_s)
            return
        if verify_lowering:
            if not on_tpu:
                print(json.dumps({
                    "metric": "TPU-lowering gate", "value": 0.0, "unit": "pass",
                    "vs_baseline": 0.0, "error": "no TPU attached", **_DIAG}))
                sys.exit(1)
            _run_lowering_gate(strict=True)
            return
        if config == 2:
            config2_nat44(on_tpu)
        elif config == 3:
            config3_qos(on_tpu)
        elif config == 4:
            config4_pppoe(on_tpu)
        elif config == 5:
            config5_sharded(on_tpu)
        elif config == 6:
            config6_dhcp_fastpath(on_tpu)
        else:
            if on_tpu and os.environ.get("BNG_SKIP_LOWERING_GATE") != "1":
                _run_lowering_gate(strict=False)
            main(on_tpu)
    except Exception as e:  # never leave the driver a bare stack trace
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(_error_line(config, f"{type(e).__name__}: {e}"))
        # bench runs degrade to an error JSON line (rc 0: the driver wants a
        # line, not a crash); the CI gate must fail loudly instead
        sys.exit(1 if verify_lowering else 0)


def chaos_overhead_bench() -> None:
    """--chaos-overhead: price the DISARMED fault_point hook on the hot
    path (PERF_NOTES §7). Two numbers:

    1. ns/call of `fault_point()` with no injector armed (a module
       global load + None compare) — the absolute cost every
       instrumented site pays;
    2. the slow-path fleet's renewal req/s measured over repeated runs,
       whose run-to-run spread is the noise floor the per-frame hook
       cost (~1 fault-point call per frame via admission.admit) must
       sit below.

    Pure host measurement — no device, no child process needed.
    """
    import timeit

    from bng_tpu.chaos.faults import SimClock, fault_point
    from bng_tpu.chaos.scenarios import (_mac, _renew, build_fleet,
                                         dora_with_retries)

    n = 2_000_000
    per_call_ns = (timeit.Timer("fp('bench.point')",
                                globals={"fp": fault_point}).timeit(n)
                   / n * 1e9)

    clock = SimClock()
    fleet, _pools, _fastpath = build_fleet(2, clock, slice_size=1024)
    macs = [_mac(i) for i in range(512)]
    leased = dora_with_retries(fleet, macs, clock)
    frames = [(i, _renew(m, leased[m], i)) for i, m in enumerate(macs)]
    reps = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _b in range(4):
            fleet.handle_batch(frames, now=clock())
        dt = time.perf_counter() - t0
        reps.append(4 * len(frames) / dt)
    mean = sum(reps) / len(reps)
    spread_pct = (max(reps) - min(reps)) / mean * 100.0
    per_frame_ns = 1e9 / mean
    overhead_pct = per_call_ns / per_frame_ns * 100.0
    print(json.dumps({
        "metric": "chaos_disarmed_overhead",
        "fault_point_ns_per_call": round(per_call_ns, 1),
        "slowpath_req_s_mean": round(mean),
        "slowpath_req_s_runs": [round(r) for r in reps],
        "run_to_run_spread_pct": round(spread_pct, 2),
        "hook_overhead_per_frame_pct": round(overhead_pct, 4),
        "below_noise": overhead_pct < spread_pct,
    }))


def telemetry_overhead_bench() -> None:
    """--telemetry-overhead: price the DISARMED telemetry span hooks on
    the hot path (PERF_NOTES §8) with the §7 methodology. Three numbers:

    1. ns/call of `spans.t()` disarmed (one module-global load + is-None
       compare — the origin half of every instrumented region);
    2. ns/call of `spans.lap()` with a None origin (the close half);
    3. the slow-path fleet's renewal req/s over repeated runs, whose
       run-to-run spread is the noise floor the per-batch hook cost
       must sit below (instrumented sites pay ~10 hook calls per BATCH,
       amortized over >= dozens of frames).

    Pure host measurement — no device, no child process needed.
    """
    import timeit

    from bng_tpu.chaos.scenarios import (_mac, _renew, build_fleet,
                                         dora_with_retries)
    from bng_tpu.chaos.faults import SimClock
    from bng_tpu.telemetry import spans

    assert not spans.enabled()
    n = 2_000_000
    t_ns = (timeit.Timer("f()", globals={"f": spans.t}).timeit(n)
            / n * 1e9)
    lap_ns = (timeit.Timer("f(3, None)",
                           globals={"f": spans.lap}).timeit(n) / n * 1e9)
    stamp_ns = (timeit.Timer("f(3)",
                             globals={"f": spans.stamp}).timeit(n) / n * 1e9)

    clock = SimClock()
    fleet, _pools, _fastpath = build_fleet(2, clock, slice_size=1024)
    macs = [_mac(i) for i in range(512)]
    leased = dora_with_retries(fleet, macs, clock)
    frames = [(i, _renew(m, leased[m], i)) for i, m in enumerate(macs)]
    reps = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _b in range(4):
            fleet.handle_batch(frames, now=clock())
        dt = time.perf_counter() - t0
        reps.append(4 * len(frames) / dt)
    mean = sum(reps) / len(reps)
    spread_pct = (max(reps) - min(reps)) / mean * 100.0
    per_frame_ns = 1e9 / mean
    # the fleet slow path pays 4 hook calls/batch (admit span + shed
    # count + fleet span) + the engine's ~8/batch; per FRAME the cost is
    # hooks/batch / frames-per-batch — bound it with the worst case of
    # one t()+lap() pair per frame
    overhead_pct = (t_ns + lap_ns) / per_frame_ns * 100.0
    print(json.dumps({
        "metric": "telemetry_disarmed_overhead",
        "span_t_ns_per_call": round(t_ns, 1),
        "span_lap_ns_per_call": round(lap_ns, 1),
        "span_stamp_ns_per_call": round(stamp_ns, 1),
        "slowpath_req_s_mean": round(mean),
        "slowpath_req_s_runs": [round(r) for r in reps],
        "run_to_run_spread_pct": round(spread_pct, 2),
        "hook_pair_per_frame_pct": round(overhead_pct, 4),
        "below_noise": overhead_pct < spread_pct,
    }))


def main_dispatch() -> None:
    """Supervisor: run the benchmark in a killable child process.

    A SIGALRM watchdog cannot interrupt a hang inside native PJRT init (the
    axon plugin blocks in C while the chip is claimed), so the only robust
    "never hang" guard is process-level: re-exec this script as a child with
    a hard timeout, forward its output, and synthesize an error JSON line if
    it dies or stalls. BNG_BENCH_CHILD=1 marks the child.
    """
    import argparse
    import subprocess

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=0,
                    help="BASELINE.json config number (1-6); 0 = headline mix")
    ap.add_argument("--verify-lowering", action="store_true",
                    help="run the TPU-lowering gate only (CI pre-step; rc=1 on failure)")
    ap.add_argument("--scheduler", action="store_true",
                    help="latency mode through the tiered scheduler: "
                         "device-isolated OFFER p50/p99 + per-lane stats "
                         "(rc=2 if lowering verification fails)")
    ap.add_argument("--checkpoint-interval-s", type=float, default=0.0,
                    help="with --scheduler: run the warm-restart snapshot "
                         "cadence during the measured loops (quiesce + "
                         "save every N seconds) to price the barrier")
    ap.add_argument("--chaos-overhead", action="store_true",
                    help="measure the disarmed fault_point hook cost vs "
                         "slow-path run-to-run noise (PERF_NOTES §7); "
                         "host-only, no device")
    ap.add_argument("--telemetry-overhead", action="store_true",
                    help="measure the disarmed telemetry span hook cost "
                         "vs slow-path run-to-run noise (PERF_NOTES §8); "
                         "host-only, no device")
    ap.add_argument("--express-ab", action="store_true",
                    help="one-flag A/B of the express-lane architectures "
                         "(ISSUE 13): jit full-program vs AOT "
                         "minimal-program express — emits one "
                         "offer_device_only_p99_us cohort per "
                         "express_path identity (rc=2 if lowering "
                         "verification fails)")
    ap.add_argument("--host-ab", action="store_true",
                    help="one-flag A/B of the HOST serving paths "
                         "(ISSUE 14): scalar per-frame vs vectorized "
                         "batch-native ring/admission/staging — emits "
                         "one summed-host-stage-p50 cohort per "
                         "host_path identity plus a speedup summary")
    ap.add_argument("--wire-ab", action="store_true",
                    help="one-flag A/B of the WIRE PUMP implementations "
                         "(ISSUE 15): scalar per-frame ctypes vs "
                         "batch-native vector over the native batch "
                         "verbs, full wire loop on the memory rung — "
                         "emits one summed-wire-stage-p50 cohort per "
                         "wire_pump identity plus a speedup summary")
    ap.add_argument("--autotune", action="store_true",
                    help="stage-breakdown-driven sweep of batch geometry "
                         "x pipeline depth x table impl (ISSUE 11): "
                         "emits a best-point JSON, appends every sweep "
                         "point to the schema'd ledger impl-keyed")
    ap.add_argument("--dry-run", action="store_true",
                    help="with --autotune: tiny CPU-safe sweep to a temp "
                         "ledger (the make verify-kernels smoke)")
    ap.add_argument("--shards", type=int, default=0,
                    help="serving-path aggregate headline (ISSUE 12): "
                         "drive the N-shard ShardedCluster through its "
                         "steered ring loop (process_ring_pipelined) "
                         "and publish aggregate Mpps with n_shards in "
                         "the ledger cohort key; on CPU the mesh is "
                         "forced host devices (tier-1 posture)")
    ap.add_argument("--require-tpu", action="store_true",
                    help="exit nonzero (rc=3) instead of publishing "
                         "CPU-fallback numbers — the CI headline gate")
    ap.add_argument("--gate", action="store_true",
                    help="after the run, trend-gate the appended ledger "
                         "line against its comparable cohort "
                         "(bng_tpu/telemetry/ledger.py); exits with the "
                         "gate rc: 0 clean / 1 regression / 2 internal "
                         "/ 3 incomparable-cohort")
    args = ap.parse_args()

    if args.chaos_overhead:
        # pure-host micro-measurement: nothing to hang on, no child
        chaos_overhead_bench()
        return
    if args.telemetry_overhead:
        telemetry_overhead_bench()
        return

    if os.environ.get("BNG_BENCH_CHILD") == "1":
        _child_dispatch(args.config, verify_lowering=args.verify_lowering,
                        scheduler=args.scheduler,
                        checkpoint_interval_s=args.checkpoint_interval_s,
                        require_tpu=args.require_tpu,
                        autotune=args.autotune,
                        autotune_dry_run=args.dry_run,
                        shards=args.shards,
                        express_ab=args.express_ab,
                        host_ab=args.host_ab,
                        wire_ab=args.wire_ab)
        return

    # BNG_BENCH_TIMEOUT bounds the benchmark itself; the probe window is
    # added on top (explicit or default), so a long capture-on-return probe
    # can never eat the run budget.
    timeout_s = (float(os.environ.get("BNG_BENCH_TIMEOUT", 2400))
                 + _probe_window())
    env = dict(os.environ)
    env["BNG_BENCH_CHILD"] = "1"
    # --gate ties its verdict to THIS run: remember how many ledger
    # lines exist before the child, so a run that appends nothing (read
    # -only checkout) or only an error line can never earn a CLEAN
    # verdict about stale history
    gate_path = gate_pre_lines = None
    if args.gate:
        from bng_tpu.telemetry import ledger

        gate_path = ledger.default_ledger_path()
        try:
            gate_pre_lines = len(ledger.read(gate_path))
        except OSError:
            gate_pre_lines = 0
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env, timeout=timeout_s, stdout=subprocess.PIPE, text=True)
        out = (res.stdout or "").strip()
        # forward the child's final JSON line (its stderr already streamed)
        json_lines = [ln for ln in out.splitlines() if ln.startswith("{")]
        if json_lines:
            print(json_lines[-1])
        else:
            print(_error_line(args.config,
                              f"child rc={res.returncode}, no JSON emitted"))
        if (args.verify_lowering or args.scheduler or args.express_ab
                or args.host_ab or args.wire_ab
                or args.require_tpu) and res.returncode != 0:
            # CI pre-step / scheduler mode / headline gate: propagate the
            # child verdict (scheduler exits 2 when lowering verification
            # refused it; --require-tpu exits 3 on CPU fallback)
            sys.exit(res.returncode)
        if args.gate:
            # the run appended its ledger line; trend-gate it now and
            # make the regression verdict THIS process's exit code —
            # but only if the candidate IS this run's line
            from bng_tpu.telemetry import ledger

            try:
                lines = ledger.read(gate_path)
            except OSError as e:
                print(f"perf gate: cannot read ledger {gate_path}: {e}",
                      file=sys.stderr)
                sys.exit(2)
            idx = ledger.newest_gateable_index(lines)
            if idx is None or idx < gate_pre_lines:
                print("perf gate: this run appended no gateable ledger "
                      f"line to {gate_path} (read-only checkout or "
                      "error run) — refusing a verdict about stale "
                      "history (rc=2)", file=sys.stderr)
                sys.exit(2)
            rep = ledger.gate(lines)
            print(rep.format_text(), file=sys.stderr)
            sys.exit(rep.rc)
    except subprocess.TimeoutExpired:
        print(_error_line(args.config,
                          f"benchmark child timed out after {timeout_s:.0f}s"))
        if (args.verify_lowering or args.scheduler or args.express_ab
                or args.host_ab or args.wire_ab or args.require_tpu
                or args.gate):
            sys.exit(1)  # a gate that never ran is a failed gate
    except Exception as e:  # pragma: no cover - spawn failure
        print(_error_line(args.config, f"supervisor error: {type(e).__name__}: {e}"))
        if (args.verify_lowering or args.scheduler or args.express_ab
                or args.host_ab or args.wire_ab or args.require_tpu
                or args.gate):
            sys.exit(1)


if __name__ == "__main__":
    main_dispatch()
